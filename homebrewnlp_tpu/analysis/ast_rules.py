"""AST lint: repo-specific rules enforcing the NT named-axis discipline.

Static source rules (no tracing, no jax beyond the axis registry import):

- ``axis-literal``: a string literal used in an axis position (NT
  construction, nd reductions/einsum/slicing, rename/transpose, spec_for)
  must be registered in the nd axis registry (``nd.register_axis``; config.py
  registers the canonical dimension constants).  Anonymized twins
  (``_sequence``) validate via their base name.  A typoed axis builds a
  silently mis-broadcast graph — this catches it at lint time.
- ``x-escape``: ``.x`` raw-array escapes outside ``ops/`` are a ratchet:
  per-file counts are pinned in a golden and may only go down.  (The ops/
  kernels legitimately live on raw arrays; model code should stay in the
  named algebra.)
- ``traced-rng``: no Python-side ``random`` / ``np.random`` / ``time`` /
  ``datetime`` calls inside traced model code (models/ and ops/) — they bake
  trace-time values into the graph and break determinism across rebuilds.
- ``partitionspec-axis``: ``PartitionSpec`` literals may only name mesh axes
  that exist (parallel/mesh.py MESH_AXES); an unknown axis is silently
  treated as replicated by the sharding machinery.
- ``host-sync``: no blocking device->host reads (``int()``/``float()``/
  ``.item()``/``block_until_ready``) inside the step loop of ``train()`` /
  ``_train_loop()`` — the async-dispatch loop (main.py,
  docs/performance.md) computes step indices on host and drains metrics
  through a deferred window; one stray ``float(loss)`` re-serializes every
  step.  Ratcheted like ``x-escape``: per-file counts pinned in
  ``goldens/ast_host_sync.json`` may only go down.
- ``obs-in-trace``: no observability calls (anything imported from the
  ``obs`` package — span tracer, metrics registry, exporter) inside
  jit-traced code (models/, ops/, infer/, optim/, train/state.py).  A
  host-side span or counter update in traced code either bakes a
  trace-time no-op into the graph or, worse, forces a host callback;
  instrumentation belongs in the host loop layers (main.py, data/feed.py,
  train/metrics.py, serve/).  ONE explicit exception:
  ``obs/device_telemetry.py`` (``OBS_IN_TRACE_ALLOWED``) is pure jnp by
  contract and is how the train step computes in-graph numerics — imports
  of/from it never count, everything else in ``obs`` stays forbidden.
  Ratcheted: per-file counts pinned in ``goldens/ast_obs_in_trace.json``
  (committed empty) may only go down.
- ``bare-io``: no unwrapped I/O in the ``train/`` and ``data/`` hot paths
  (docs/reliability.md) — builtin ``open()`` calls, orbax
  ``CheckpointManager`` construction, and ``manager.save/restore/
  wait_until_finished`` calls must route through the reliability retry
  layer (``reliability.retry``) or ``data/fs.py``; a call-site the retry
  wrapper itself invokes carries a ``graftcheck: disable=bare-io``
  suppression marking it as wrapped.  Ratcheted at ZERO: the committed
  golden ``goldens/ast_bare_io.json`` is empty, so any new bare call is an
  error.  (``data/fs.py`` — the I/O switch-point — and ``data/synthetic.py``
  — test-fixture generation — are exempt.)

Suppression: append ``# graftcheck: disable=<rule>`` (or a bare
``# graftcheck: disable``) to the offending line.
"""
from __future__ import annotations

import ast
import json
import os
import typing

from .findings import Finding

# rule -> relative directories it applies to (package-relative)
AXIS_LITERAL_SCOPE = ("homebrewnlp_tpu/models", "homebrewnlp_tpu/ops",
                      "homebrewnlp_tpu/infer", "homebrewnlp_tpu/data")
TRACED_RNG_SCOPE = ("homebrewnlp_tpu/models", "homebrewnlp_tpu/ops")
X_ESCAPE_SCOPE = "homebrewnlp_tpu"
X_ESCAPE_EXEMPT = ("homebrewnlp_tpu/ops", "homebrewnlp_tpu/nd.py",
                   "homebrewnlp_tpu/analysis")

#: call-name -> axis argument positions.  Each entry: (positional index
#: AFTER any self, keyword name, kind) with kind "name" (one string) or
#: "seq" (tuple/list of strings).  Matching is by the call's terminal name,
#: so both ``nd.concat(...)`` and ``concat(...)`` resolve.
AXIS_CALLS: typing.Dict[str, typing.Tuple[typing.Tuple[int, str, str], ...]] = {
    "NT": ((1, "names", "seq"),),
    "einsum": ((1, "out_names", "seq"),),
    "reduce_sum": ((1, "reduced", "seq"), (2, "out_names", "seq")),
    "reduce_mean": ((1, "reduced", "seq"), (2, "out_names", "seq")),
    "reduce_max": ((1, "reduced", "seq"), (2, "out_names", "seq")),
    "reduce_min": ((1, "reduced", "seq"), (2, "out_names", "seq")),
    "nt_slice": ((1, "axis", "name"),),
    "concat": ((1, "axis", "name"),),
    "pad": ((1, "axis", "name"),),
    "one_hot": ((1, "axis_name", "name"),),
    "arange": ((0, "name", "name"),),
    "cumsum": ((1, "axis", "name"),),
    "full": ((0, "names", "seq"),),
    "compare_range": ((0, "name0", "name"), (2, "name1", "name")),
    "rename": ((0, "old", "name"), (1, "new", "name")),
    "transpose_to": ((0, "names", "seq"),),
    "expand": ((0, "name", "name"),),
    "dim_size": ((0, "name", "name"),),
    "spec_for": ((0, "names", "seq"),),
}

_RNG_MODULES = {"random", "time", "datetime"}


def _known_axes() -> typing.FrozenSet[str]:
    # import every module that calls nd.register_axis so the registry is
    # complete regardless of what else this process imported: config.py
    # (canonical dimension constants) and the layer library (layer-local
    # scratch axes like "rows")
    from .. import config  # noqa: F401
    from .. import nd
    from ..models import layers  # noqa: F401
    return nd.known_axes()


def _mesh_axes() -> typing.FrozenSet[str]:
    from ..parallel.mesh import MESH_AXES
    return frozenset(MESH_AXES)


def _suppressed(lines: typing.Sequence[str], lineno: int, rule: str) -> bool:
    if not 1 <= lineno <= len(lines):
        return False
    line = lines[lineno - 1]
    if "graftcheck: disable" not in line:
        return False
    tail = line.split("graftcheck: disable", 1)[1]
    return not tail.startswith("=") or rule in tail[1:].replace(",", " ").split()


def _terminal_name(func: ast.expr) -> typing.Optional[str]:
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def _axis_literals(node: ast.expr, kind: str) -> typing.List[ast.Constant]:
    out: typing.List[ast.Constant] = []
    if kind == "name":
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            out.append(node)
    else:  # seq
        if isinstance(node, (ast.Tuple, ast.List)):
            for el in node.elts:
                if isinstance(el, ast.Constant) and isinstance(el.value, str):
                    out.append(el)
    return out


def _valid_axis(name: str, registry: typing.FrozenSet[str]) -> bool:
    if name == "":
        return True
    base = name[1:] if name.startswith("_") else name
    return name in registry or base in registry


def _iter_py_files(root: str, scopes: typing.Sequence[str]
                   ) -> typing.Iterator[typing.Tuple[str, str]]:
    """Yield (abs_path, rel_path) of every .py file under the scopes."""
    for scope in scopes:
        top = os.path.join(root, scope)
        if os.path.isfile(top):
            yield top, scope
            continue
        for dirpath, _, files in os.walk(top):
            for fn in sorted(files):
                if fn.endswith(".py"):
                    p = os.path.join(dirpath, fn)
                    yield p, os.path.relpath(p, root)


def check_axis_literals(root: str) -> typing.List[Finding]:
    registry = _known_axes()
    findings: typing.List[Finding] = []
    for path, rel in _iter_py_files(root, AXIS_LITERAL_SCOPE):
        src = open(path).read()
        lines = src.splitlines()
        tree = ast.parse(src, filename=rel)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            name = _terminal_name(node.func)
            spec = AXIS_CALLS.get(name or "")
            if spec is None:
                continue
            # table indices are AST-positional: bound method calls
            # (t.rename(a, b)) never carry self in node.args, and the
            # method-only entries are written method-relative
            for idx, kw, kind in spec:
                arg_node = None
                if 0 <= idx < len(node.args):
                    arg_node = node.args[idx]
                for k in node.keywords:
                    if k.arg == kw:
                        arg_node = k.value
                if arg_node is None:
                    continue
                for lit in _axis_literals(arg_node, kind):
                    if _valid_axis(lit.value, registry):
                        continue
                    if _suppressed(lines, lit.lineno, "axis-literal"):
                        continue
                    findings.append(Finding(
                        "axis-literal", "error", f"{rel}:{lit.lineno}",
                        f"axis name {lit.value!r} (arg {kw!r} of {name}) is "
                        f"not in the nd axis registry — register it with "
                        f"nd.register_axis or fix the typo"))
    return findings


def x_escape_counts(root: str) -> typing.Dict[str, int]:
    counts: typing.Dict[str, int] = {}
    for path, rel in _iter_py_files(root, (X_ESCAPE_SCOPE,)):
        norm = rel.replace(os.sep, "/")
        if any(norm == e or norm.startswith(e + "/") for e in X_ESCAPE_EXEMPT):
            continue
        tree = ast.parse(open(path).read(), filename=rel)
        n = sum(1 for node in ast.walk(tree)
                if isinstance(node, ast.Attribute) and node.attr == "x"
                and isinstance(node.ctx, ast.Load))
        if n:
            counts[norm] = n
    return counts


def x_escape_golden_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "goldens", "ast_x_escapes.json")


def _check_ratchet(rule: str, counts: typing.Dict[str, int], path: str,
                   update_goldens: bool, unit: str, over_hint: str
                   ) -> typing.List[Finding]:
    """Shared golden-ratchet machinery for per-file count rules (x-escape,
    host-sync): counts pinned in a committed golden may only go DOWN; a
    count above the golden is an error (with ``over_hint`` naming the fix),
    below is an info asking to re-record; ``--update-goldens`` re-records."""
    if update_goldens:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as f:
            json.dump(counts, f, indent=2, sort_keys=True)
            f.write("\n")
        return [Finding(rule, "info", path,
                        f"ratchet updated ({sum(counts.values())} {unit} in "
                        f"{len(counts)} files)")]
    if not os.path.exists(path):
        return [Finding(rule, "error", path,
                        f"no {rule} ratchet golden; run --update-goldens")]
    golden = json.load(open(path))
    findings: typing.List[Finding] = []
    for rel in sorted(set(counts) | set(golden)):
        got, want = counts.get(rel, 0), golden.get(rel, 0)
        if got > want:
            findings.append(Finding(
                rule, "error", rel,
                f"{got} {unit} (ratchet allows {want}) — {over_hint}, or "
                f"re-record with --update-goldens if deliberate"))
        elif got < want:
            findings.append(Finding(
                rule, "info", rel,
                f"{unit} improved {want} -> {got}; re-record the ratchet "
                f"with --update-goldens"))
    return findings


def check_x_escapes(root: str, update_goldens: bool = False
                    ) -> typing.List[Finding]:
    return _check_ratchet(
        "x-escape", x_escape_counts(root), x_escape_golden_path(),
        update_goldens, unit="raw .x escapes",
        over_hint="keep model code in the named-axis algebra")


#: files whose train step loop the host-sync rule audits
HOST_SYNC_SCOPE = ("homebrewnlp_tpu/main.py",)
#: function names holding the audited step loop (train() wraps the obs
#: lifecycle; _train_loop() carries the actual loop since the obs PR)
HOST_SYNC_FUNCS = frozenset({"train", "_train_loop"})
#: builtins whose call on a device value forces a D2H sync
HOST_SYNC_CALLS = frozenset({"int", "float", "bool"})
#: method names that force a D2H sync (or a full-device barrier)
HOST_SYNC_METHODS = frozenset({"item", "block_until_ready"})


def host_sync_counts(root: str) -> typing.Dict[str, int]:
    """Per-file counts of potentially-blocking host reads inside loop bodies
    of the step-loop functions (``HOST_SYNC_FUNCS``).  Purely syntactic (no
    type inference): any ``int(...)``/``float(...)``/``bool(...)`` call or
    ``.item()``/``.block_until_ready()`` method call in the loop counts —
    host-only arithmetic belongs outside the loop or behind a suppression,
    which is exactly the ratchet discipline."""
    counts: typing.Dict[str, int] = {}
    for path, rel in _iter_py_files(root, HOST_SYNC_SCOPE):
        src = open(path).read()
        lines = src.splitlines()
        tree = ast.parse(src, filename=rel)
        hits: typing.Set[int] = set()  # node ids: nested loops walk twice
        for fn in ast.walk(tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if fn.name not in HOST_SYNC_FUNCS:
                continue
            for loop in ast.walk(fn):
                if not isinstance(loop, (ast.For, ast.While)):
                    continue
                for node in ast.walk(loop):
                    if not isinstance(node, ast.Call):
                        continue
                    sync = ((isinstance(node.func, ast.Name)
                             and node.func.id in HOST_SYNC_CALLS)
                            or (isinstance(node.func, ast.Attribute)
                                and node.func.attr in HOST_SYNC_METHODS))
                    if sync and not _suppressed(lines, node.lineno,
                                               "host-sync"):
                        hits.add(id(node))
        if hits:
            counts[rel.replace(os.sep, "/")] = len(hits)
    return counts


def host_sync_golden_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "goldens", "ast_host_sync.json")


def check_host_sync(root: str, update_goldens: bool = False
                    ) -> typing.List[Finding]:
    return _check_ratchet(
        "host-sync", host_sync_counts(root), host_sync_golden_path(),
        update_goldens,
        unit="blocking device->host read(s) inside train()'s step loop",
        over_hint="int()/float()/.item()/block_until_ready re-serializes "
                  "the async-dispatch loop (docs/performance.md); compute "
                  "step indices on host and route metrics through the "
                  "deferred drain")


#: jit-traced scopes the obs-in-trace rule forbids span/registry calls in.
#: train/state.py joined with the device-telemetry PR: the step function it
#: builds IS traced code, and it legitimately imports the one allowlisted
#: obs module below.
OBS_IN_TRACE_SCOPE = ("homebrewnlp_tpu/models", "homebrewnlp_tpu/ops",
                      "homebrewnlp_tpu/infer", "homebrewnlp_tpu/optim",
                      "homebrewnlp_tpu/train/state.py")

#: the ONE obs module legal in traced code: ``obs/device_telemetry.py`` is
#: pure jnp by contract (its host half runs only in the metric drain), so
#: imports of/from it never count — every other obs module (spans,
#: registry, exporter) stays forbidden in the traced scopes.
OBS_IN_TRACE_ALLOWED = frozenset({"device_telemetry"})


def _obs_aliases(tree: ast.Module
                 ) -> typing.Tuple[typing.Set[str], typing.Set[str]]:
    """(direct aliases, dotted roots) bound to the ``obs`` package.

    Direct aliases name an obs object outright: ``from ..obs import
    spans``, ``from homebrewnlp_tpu.obs.spans import span``, ``import
    homebrewnlp_tpu.obs.registry as reg``, ``from .. import obs``.  Dotted
    roots come from a bare ``import homebrewnlp_tpu.obs.spans``: only the
    TOP-LEVEL name is bound, so a call through it counts only when its
    attribute chain passes through ``obs`` (otherwise ``homebrewnlp_tpu.nd
    .register_axis(...)`` in the same file would be miscounted).

    Imports of (or from) an ``OBS_IN_TRACE_ALLOWED`` module bind nothing:
    ``from ..obs import device_telemetry`` / ``from
    ..obs.device_telemetry import collect`` are the sanctioned way for
    traced code to reach the in-graph telemetry."""
    aliases: typing.Set[str] = set()
    dotted_roots: typing.Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            parts = mod.split(".")
            if OBS_IN_TRACE_ALLOWED & set(parts):
                continue  # importing FROM the allowlisted module
            if "obs" in parts:
                for a in node.names:
                    if a.name in OBS_IN_TRACE_ALLOWED:
                        continue  # `from ..obs import device_telemetry`
                    aliases.add(a.asname or a.name)
            else:  # the package imported as a name: `from .. import obs`
                for a in node.names:
                    if a.name == "obs":
                        aliases.add(a.asname or a.name)
        elif isinstance(node, ast.Import):
            for a in node.names:
                parts = a.name.split(".")
                if "obs" not in parts:
                    continue
                if a.asname is not None or parts[0] == "obs":
                    # direct alias: skip the binding ONLY when it names the
                    # allowlisted module itself (`import ...device_telemetry
                    # as dt`)
                    if not OBS_IN_TRACE_ALLOWED & set(parts):
                        aliases.add(a.asname or parts[0])
                else:
                    # bare dotted import binds the TOP-LEVEL name: track the
                    # root even for an allowlisted module — the chain filter
                    # at the call site decides, so `import homebrewnlp_tpu.
                    # obs.device_telemetry` cannot whitelist a sibling
                    # `homebrewnlp_tpu.obs.spans.span(...)` in the same file
                    dotted_roots.add(parts[0])
    return aliases, dotted_roots


def obs_in_trace_counts(root: str) -> typing.Dict[str, int]:
    """Per-file counts of calls rooted at an obs-package alias inside the
    traced scopes.  Purely syntactic: every Call node whose chain roots at
    an obs alias counts, so ``span(...)`` and ``spans.span(...)`` count 1
    and a chained ``obs.REGISTRY.counter(...).inc()`` counts 2 (the
    ``.counter`` call and the ``.inc`` call) — the ratchet unit is 'obs
    call sites', not statements."""
    counts: typing.Dict[str, int] = {}
    for path, rel in _iter_py_files(root, OBS_IN_TRACE_SCOPE):
        src = open(path).read()
        if "obs" not in src:
            continue
        lines = src.splitlines()
        tree = ast.parse(src, filename=rel)
        aliases, dotted_roots = _obs_aliases(tree)
        if not aliases and not dotted_roots:
            continue
        n = 0
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            chain: typing.List[str] = []
            cur: ast.expr = node.func
            while isinstance(cur, (ast.Attribute, ast.Call)):
                if isinstance(cur, ast.Attribute):
                    chain.append(cur.attr)
                cur = cur.func if isinstance(cur, ast.Call) else cur.value
            if not isinstance(cur, ast.Name):
                continue
            rooted = cur.id in aliases or (cur.id in dotted_roots
                                           and "obs" in chain)
            # chain-level allowlist: a call whose attribute path passes
            # through device_telemetry (`obs.device_telemetry.collect(...)`)
            # is the sanctioned in-graph telemetry, whatever root it rides
            hit = rooted and not (OBS_IN_TRACE_ALLOWED & set(chain))
            if hit and not _suppressed(lines, node.lineno, "obs-in-trace"):
                n += 1
        if n:
            counts[rel.replace(os.sep, "/")] = n
    return counts


def obs_in_trace_golden_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "goldens", "ast_obs_in_trace.json")


def check_obs_in_trace(root: str, update_goldens: bool = False
                       ) -> typing.List[Finding]:
    return _check_ratchet(
        "obs-in-trace", obs_in_trace_counts(root), obs_in_trace_golden_path(),
        update_goldens,
        unit="obs span/registry call(s) in jit-traced code",
        over_hint="host observability inside traced code bakes a no-op into "
                  "the graph (or forces a host callback); instrument the "
                  "host loop layers instead — in-graph numerics belong in "
                  "the allowlisted obs/device_telemetry.py "
                  "(docs/observability.md)")


#: hot paths the bare-io rule audits: every I/O call here must go through
#: the reliability retry layer (or fs.py) so a transient storage error
#: cannot kill a run
BARE_IO_SCOPE = ("homebrewnlp_tpu/train", "homebrewnlp_tpu/data")
#: fs.py IS the I/O layer; synthetic.py writes test fixtures only
BARE_IO_EXEMPT = ("homebrewnlp_tpu/data/fs.py",
                  "homebrewnlp_tpu/data/synthetic.py")
#: orbax CheckpointManager method calls that hit storage
BARE_IO_MANAGER_METHODS = frozenset({"save", "restore",
                                     "wait_until_finished"})


def _orbax_aliases(tree: ast.Module
                   ) -> typing.Tuple[typing.Set[str], typing.Set[str]]:
    """(orbax module aliases, CheckpointManager-constructor aliases).

    ``import orbax.checkpoint as ocp`` -> ({"ocp"}, {}); ``from
    orbax.checkpoint import CheckpointManager as CM`` -> ({"CM"}, {"CM"})
    — tracking the imported TARGET name means an alias cannot slip the
    constructor past the ratchet."""
    aliases: typing.Set[str] = set()
    ctor_aliases: typing.Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name.split(".")[0] == "orbax":
                    aliases.add(a.asname or a.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom):
            if (node.module or "").split(".")[0] == "orbax":
                for a in node.names:
                    local = a.asname or a.name
                    aliases.add(local)
                    if a.name == "CheckpointManager":
                        ctor_aliases.add(local)
    return aliases, ctor_aliases


def bare_io_counts(root: str) -> typing.Dict[str, int]:
    """Per-file counts of unwrapped I/O calls in the hot paths: builtin
    ``open(...)``, orbax ``CheckpointManager(...)`` construction (rooted at
    an orbax alias), and ``<...>.manager.save/restore/wait_until_finished``
    calls.  Purely syntactic; a site invoked THROUGH the retry layer is
    marked with ``# graftcheck: disable=bare-io`` on its line."""
    counts: typing.Dict[str, int] = {}
    for path, rel in _iter_py_files(root, BARE_IO_SCOPE):
        norm = rel.replace(os.sep, "/")
        if any(norm == e or norm.startswith(e + "/") for e in BARE_IO_EXEMPT):
            continue
        src = open(path).read()
        lines = src.splitlines()
        tree = ast.parse(src, filename=rel)
        orbax_aliases, ctor_aliases = _orbax_aliases(tree)
        n = 0
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            hit = False
            if isinstance(node.func, ast.Name):
                # builtin open, or a from-imported (possibly aliased)
                # orbax CheckpointManager
                hit = node.func.id == "open" or node.func.id in ctor_aliases
            elif isinstance(node.func, ast.Attribute):
                # chain outward-in: self.manager.save -> ["save", "manager"]
                chain: typing.List[str] = []
                cur: ast.expr = node.func
                while isinstance(cur, ast.Attribute):
                    chain.append(cur.attr)
                    cur = cur.value
                rooted_orbax = (isinstance(cur, ast.Name)
                                and cur.id in orbax_aliases)
                hit = ((rooted_orbax and chain[0] == "CheckpointManager")
                       or (chain[0] in BARE_IO_MANAGER_METHODS
                           and "manager" in chain[1:]))
            if hit and not _suppressed(lines, node.lineno, "bare-io"):
                n += 1
        if n:
            counts[norm] = n
    return counts


def bare_io_golden_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "goldens", "ast_bare_io.json")


def check_bare_io(root: str, update_goldens: bool = False
                  ) -> typing.List[Finding]:
    return _check_ratchet(
        "bare-io", bare_io_counts(root), bare_io_golden_path(),
        update_goldens,
        unit="unwrapped open()/orbax call(s) in train/data hot paths",
        over_hint="route storage I/O through reliability.retry (or "
                  "data/fs.py) so transient errors back off instead of "
                  "killing the run (docs/reliability.md)")


def check_traced_rng(root: str) -> typing.List[Finding]:
    findings: typing.List[Finding] = []
    for path, rel in _iter_py_files(root, TRACED_RNG_SCOPE):
        src = open(path).read()
        lines = src.splitlines()
        tree = ast.parse(src, filename=rel)
        # module aliases imported in this file: {"random", "time", ...} plus
        # numpy aliases for the np.random case
        mod_alias: typing.Dict[str, str] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    mod_alias[a.asname or a.name.split(".")[0]] = \
                        a.name.split(".")[0]
            elif isinstance(node, ast.ImportFrom) and node.module:
                base = node.module.split(".")[0]
                if base in _RNG_MODULES:
                    for a in node.names:
                        mod_alias[a.asname or a.name] = base
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            root_name = None
            chain: typing.List[str] = []
            cur: ast.expr = func
            while isinstance(cur, ast.Attribute):
                chain.append(cur.attr)
                cur = cur.value
            if isinstance(cur, ast.Name):
                root_name = cur.id
                chain.append(cur.id)
            chain.reverse()
            bad = None
            if root_name and mod_alias.get(root_name) in _RNG_MODULES:
                bad = ".".join(chain)
            elif (root_name and mod_alias.get(root_name) == "numpy"
                    and len(chain) >= 2 and chain[1] == "random"):
                bad = ".".join(chain)
            if bad and not _suppressed(lines, node.lineno, "traced-rng"):
                findings.append(Finding(
                    "traced-rng", "error", f"{rel}:{node.lineno}",
                    f"host-side call {bad}() inside traced model code — it "
                    f"bakes a trace-time value into the graph; use jax.random "
                    f"via ctx.next_rng() (or hoist it out of models/ops)"))
    return findings


#: scopes where an f64 dtype request is always a defect: model/op/optimizer
#: code gets its dtypes from the config policy knobs, never from literals.
#: (The graph-level f64 audit in graph_rules only sees real f64 avals, which
#: jax's default x64-disabled mode silently squashes to f32 — this static
#: check catches the request itself.)
F64_SCOPE = ("homebrewnlp_tpu/models", "homebrewnlp_tpu/ops",
             "homebrewnlp_tpu/optim", "homebrewnlp_tpu/train")


def check_f64_literals(root: str) -> typing.List[Finding]:
    findings: typing.List[Finding] = []
    for path, rel in _iter_py_files(root, F64_SCOPE):
        src = open(path).read()
        if "float64" not in src and "complex128" not in src:
            continue
        lines = src.splitlines()
        tree = ast.parse(src, filename=rel)
        for node in ast.walk(tree):
            hit = None
            if (isinstance(node, ast.Attribute)
                    and node.attr in ("float64", "complex128")):
                hit = node.attr
            elif (isinstance(node, ast.Constant)
                    and node.value in ("float64", "complex128")):
                hit = node.value
            if hit and not _suppressed(lines, node.lineno, "dtype-promotion"):
                findings.append(Finding(
                    "dtype-promotion", "error", f"{rel}:{node.lineno}",
                    f"{hit} dtype request in traced/optimizer code — jax's "
                    f"default x64-disabled mode silently computes f32 here "
                    f"while a TPU x64 run would double every byte; take the "
                    f"dtype from the config policy instead"))
    return findings


def check_partitionspec_literals(root: str) -> typing.List[Finding]:
    mesh_axes = _mesh_axes()
    findings: typing.List[Finding] = []
    for path, rel in _iter_py_files(root, ("homebrewnlp_tpu", "tools")):
        src = open(path).read()
        if "PartitionSpec" not in src:
            continue
        lines = src.splitlines()
        tree = ast.parse(src, filename=rel)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            if _terminal_name(node.func) != "PartitionSpec":
                continue
            # one spec entry may also be a tuple of axes (sharding one dim
            # over several mesh axes) — check the nested literals too
            flat: typing.List[ast.expr] = []
            for arg in node.args:
                if isinstance(arg, (ast.Tuple, ast.List)):
                    flat.extend(arg.elts)
                else:
                    flat.append(arg)
            for arg in flat:
                if not (isinstance(arg, ast.Constant)
                        and isinstance(arg.value, str)):
                    continue
                if arg.value in mesh_axes:
                    continue
                if _suppressed(lines, arg.lineno, "partitionspec-axis"):
                    continue
                findings.append(Finding(
                    "partitionspec-axis", "error", f"{rel}:{arg.lineno}",
                    f"PartitionSpec names unknown mesh axis {arg.value!r} "
                    f"(known: {sorted(mesh_axes)}) — the sharding machinery "
                    f"silently replicates unknown axes"))
    return findings


def _sync_rule(fn_name: str, root: str, update_goldens: bool
               ) -> typing.List[Finding]:
    from . import concurrency
    return getattr(concurrency, fn_name)(root, update_goldens)


def run_ast_rules(root: str, update_goldens: bool = False,
                  rules: typing.Optional[typing.Sequence[str]] = None
                  ) -> typing.List[Finding]:
    table = {
        "axis-literal": lambda: check_axis_literals(root),
        "x-escape": lambda: check_x_escapes(root, update_goldens),
        "traced-rng": lambda: check_traced_rng(root),
        "partitionspec-axis": lambda: check_partitionspec_literals(root),
        # static twin of graph_rules.check_dtype_promotion (x64-off traces
        # cannot carry real f64 avals, so the request itself is linted)
        "dtype-promotion": lambda: check_f64_literals(root),
        "host-sync": lambda: check_host_sync(root, update_goldens),
        "obs-in-trace": lambda: check_obs_in_trace(root, update_goldens),
        "bare-io": lambda: check_bare_io(root, update_goldens),
        # concurrency audit (analysis/concurrency.py): shared-state ratchet
        # + lock-order golden over the declared-lock model
        "sync-shared-state": lambda: _sync_rule(
            "check_shared_state", root, update_goldens),
        "sync-lock-order": lambda: _sync_rule(
            "check_lock_order", root, update_goldens),
    }
    findings: typing.List[Finding] = []
    for name, fn in table.items():
        if rules is None or name in rules:
            findings.extend(fn())
    return findings
