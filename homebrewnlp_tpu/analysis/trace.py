"""Abstract tracing harness: config -> jaxprs of its train/eval/decode steps.

Everything here runs on CPU with ShapeDtypeStruct parameters — ``jax.jit(
step).trace(...)`` / ``jax.make_jaxpr`` stage the computation out without
allocating parameter memory, running FLOPs, or invoking XLA, so auditing the
flagship configs (billions of abstract parameter elements) takes seconds on a
laptop.  The resulting :class:`StepTrace` bundles expose:

- ``jaxpr``      — the ClosedJaxpr rule passes walk (:func:`iter_eqns`)
- ``args_info``  — donation metadata (train step only): the pytree of
  ``jax.stages.ArgInfo`` for the step's arguments
- ``mesh``       — the concrete mesh the step was traced under

Toolchain compatibility: the pipeline/ring modules target the jax >= 0.8
``jax.shard_map`` API (``axis_names=``, vma typing, ``jax.lax.pcast``).  On
older toolchains those attributes are missing and the parallel-composed
configs could not even be *traced* — so :func:`trace_compat` provides
TRACE-ONLY shims (``jax.experimental.shard_map`` with ``auto=``, identity
``pcast``) inside a restoring context manager.  The shims are sufficient for
staging out the jaxpr and counting collectives; they are NOT numerically
faithful for execution (untyped transpose semantics) and are never installed
outside an active trace.  Census counts exclude the vma-typing bookkeeping
primitives (``pvary``/``pbroadcast``) so goldens generated under the shims
match newer toolchains.
"""
from __future__ import annotations

import contextlib
import dataclasses
import typing

import jax
import jax.numpy as jnp
import numpy as np

from ..config import Config
from ..data.feed import axes_for
from ..data.synthetic import synthetic_text_batch, synthetic_video_batch
from ..models import build, pipeline_params_stacked, stack_pipeline_params
from ..models.ctx import Ctx
from ..nd import NT
from ..optim import Optimizer
from ..parallel import make_mesh
from ..train.state import Trainer, TrainState

#: data-moving collective primitives the census counts, with cross-version
#: name normalization.  vma bookkeeping (pvary/pbroadcast) is deliberately
#: absent: it moves no bytes and differs between typed/untyped toolchains.
COLLECTIVE_PRIMS: typing.Dict[str, str] = {
    "psum": "psum",
    "psum2": "psum",
    "psum_invariant": "psum",
    "all_gather": "all_gather",
    "all_gather_invariant": "all_gather",
    "all_to_all": "all_to_all",
    "ppermute": "ppermute",
    "reduce_scatter": "reduce_scatter",
    "psum_scatter": "reduce_scatter",
    "pgather": "pgather",
    "sharding_constraint": "sharding_constraint",
}


@dataclasses.dataclass
class StepTrace:
    name: str  # "train" | "eval" | "decode" | "prefill"
    jaxpr: typing.Any  # jax.core.ClosedJaxpr
    mesh: typing.Any
    args_info: typing.Any = None  # pytree of jax.stages.ArgInfo (train only)
    state_info: typing.Any = None  # the TrainState subtree of args_info
    #: logical axis names per flattened jaxpr input (the SPMD propagation
    #: seeds, analysis/spmd.py): one entry per invar — a tuple of axis
    #: names (possibly empty = replicated) or None (sharding unknown; the
    #: propagation follows instead of charging).  None entirely when the
    #: trace path could not build the seed list.
    in_axes: typing.Optional[typing.List[
        typing.Optional[typing.Tuple[str, ...]]]] = None


@dataclasses.dataclass
class ConfigTraces:
    config_name: str
    cfg: Config
    mesh: typing.Any
    steps: typing.Dict[str, StepTrace]
    param_axes: typing.Dict[str, typing.Tuple[str, ...]]
    param_shapes: typing.Dict[str, typing.Any]  # name -> ShapeDtypeStruct
    errors: typing.Dict[str, str]  # step -> repr of trace failure
    # abstract optimizer-slot shapes + their sharding axis names (for the
    # cost model's exact param+slot byte accounting); {} when params failed
    opt_state_shapes: typing.Dict[str, typing.Dict[str, typing.Any]] = (
        dataclasses.field(default_factory=dict))
    slot_axes: typing.Dict[str, typing.Dict[str, typing.Tuple[str, ...]]] = (
        dataclasses.field(default_factory=dict))


@contextlib.contextmanager
def trace_compat():
    """Install trace-only jax API shims for toolchains older than the
    ``jax.shard_map`` / vma-typing surface the parallel modules target; a
    no-op (beyond bookkeeping) when the real APIs exist.  Always restores."""
    saved: typing.List[typing.Tuple[typing.Any, str, typing.Any, bool]] = []

    def patch(obj, name, value):
        saved.append((obj, name, getattr(obj, name, None), hasattr(obj, name)))
        setattr(obj, name, value)

    try:
        if not hasattr(jax, "shard_map"):
            from jax.experimental.shard_map import shard_map as _sm

            def shard_map(f, mesh=None, in_specs=None, out_specs=None,
                          axis_names=None, check_vma=None, **kw):
                if mesh is None:
                    from jax._src.mesh import thread_resources
                    mesh = thread_resources.env.physical_mesh
                auto = frozenset()
                if axis_names is not None:
                    auto = frozenset(mesh.axis_names) - frozenset(axis_names)
                return _sm(f, mesh=mesh, in_specs=in_specs,
                           out_specs=out_specs, check_rep=False, auto=auto)

            patch(jax, "shard_map", shard_map)
        if not hasattr(jax.lax, "pcast"):
            patch(jax.lax, "pcast", lambda x, axes, to=None: x)
        if not hasattr(jax, "typeof"):
            patch(jax, "typeof", lambda x: jax.core.get_aval(x))
        if not hasattr(jax.sharding, "get_abstract_mesh"):
            class _NoManual:
                manual_axes = ()

            patch(jax.sharding, "get_abstract_mesh", lambda: _NoManual())
        yield
    finally:
        for obj, name, old, existed in reversed(saved):
            if existed:
                setattr(obj, name, old)
            else:
                delattr(obj, name)


def iter_eqns(jaxpr) -> typing.Iterator:
    """Yield every equation of ``jaxpr`` (ClosedJaxpr or Jaxpr) and of every
    sub-jaxpr reachable through equation params (pjit/scan/while/cond/
    custom_vjp/shard_map/checkpoint), one yield per static call site."""
    inner = jaxpr.jaxpr if hasattr(jaxpr, "jaxpr") else jaxpr
    for eqn in inner.eqns:
        yield eqn
        for v in eqn.params.values():
            vals = v if isinstance(v, (list, tuple)) else [v]
            for item in vals:
                if hasattr(item, "eqns") or (
                        hasattr(item, "jaxpr")
                        and hasattr(item.jaxpr, "eqns")):
                    yield from iter_eqns(item)


def iter_closed_jaxprs(jaxpr, _seen=None) -> typing.Iterator:
    """Yield ``jaxpr`` and every nested ClosedJaxpr once (for const walks)."""
    if _seen is None:
        _seen = set()
    if id(jaxpr) in _seen:
        return
    _seen.add(id(jaxpr))
    yield jaxpr
    inner = jaxpr.jaxpr if hasattr(jaxpr, "jaxpr") else jaxpr
    for eqn in inner.eqns:
        for v in eqn.params.values():
            vals = v if isinstance(v, (list, tuple)) else [v]
            for item in vals:
                if hasattr(item, "eqns") or (
                        hasattr(item, "jaxpr")
                        and hasattr(item.jaxpr, "eqns")):
                    yield from iter_closed_jaxprs(item, _seen)


def eqn_location(eqn) -> str:
    """Best-effort ``file:line (fn)`` of an equation's user frame."""
    try:
        from jax._src import source_info_util
        return source_info_util.summarize(eqn.source_info)
    except Exception:
        return "<unknown>"


def abstract_batch(cfg: Config) -> typing.Dict[str, NT]:
    """Model-input batch with the exact shapes the data pipeline delivers
    (synthetic generators are the format reference), as tiny concrete arrays
    — token ids and masks only, never activations."""
    raw = (synthetic_video_batch(cfg) if cfg.use_video
           else synthetic_text_batch(cfg))
    return {k: NT(jnp.asarray(v), axes_for(k, v, cfg)) for k, v in raw.items()}


def abstract_params(cfg: Config, batch: typing.Dict[str, NT]
                    ) -> typing.Tuple[typing.Dict[str, typing.Any],
                                      typing.Dict[str, typing.Tuple[str, ...]]]:
    """(ShapeDtypeStruct params, axis-name metadata) via eval_shape — the
    abstract twin of ``models.init_params`` (no QR inits, no memory)."""
    meta: typing.Dict[str, typing.Tuple[str, ...]] = {}

    def _collect():
        ctx = Ctx(cfg, params=None, seed=0, train=False)
        build(ctx, batch)
        meta.update(ctx.axis_names)
        return ctx.collected

    params = jax.eval_shape(_collect)
    params, meta = dict(params), dict(meta)
    if cfg.pipeline_parallel > 1:
        # stage-stacked layout, abstractly: shapes via eval_shape, axis
        # metadata via a dummy value tree (the axis transform only needs keys)
        dummy = {k: np.zeros((1,), np.int8) for k in params}
        _, meta = stack_pipeline_params(cfg, dummy, meta)
        params = jax.eval_shape(lambda p: stack_pipeline_params(cfg, p),
                                params)
        assert pipeline_params_stacked(cfg, params)
    return params, meta


def _dict_axes(d: typing.Dict[str, typing.Any],
               fn: typing.Callable[[str], typing.Any]) -> typing.List:
    """Per-leaf seed entries of a flat dict in jax's flatten order (sorted
    keys) — the building block of a StepTrace's ``in_axes``."""
    return [fn(k) for k in sorted(d)]


def _param_in_axes(params: typing.Dict[str, typing.Any],
                   axes: typing.Dict[str, typing.Tuple[str, ...]]
                   ) -> typing.List:
    """Seed entries for a params dict: known axis metadata, else unknown
    (e.g. pipeline-unstacked decode params whose names left the metadata)."""
    return _dict_axes(params, lambda k: tuple(axes[k]) if k in axes else None)


def _check_in_axes(jaxpr, entries: typing.List
                   ) -> typing.Optional[typing.List]:
    """The seed list is only usable when it aligns 1:1 with the flattened
    invars; a mismatch (an arg subtree flattened differently than the seed
    construction assumed) degrades to None — the propagation then skips the
    step with a finding instead of mis-seeding silently."""
    inner = jaxpr.jaxpr if hasattr(jaxpr, "jaxpr") else jaxpr
    return entries if len(entries) == len(inner.invars) else None


def _micro_sds(batch: typing.Dict[str, NT], n_micro: int
               ) -> typing.Dict[str, NT]:
    if n_micro <= 1:
        return batch
    return {k: NT(jnp.zeros((t.x.shape[0] // n_micro,) + t.x.shape[1:],
                            t.x.dtype), t.names)
            for k, t in batch.items()}


def trace_train(cfg: Config, mesh=None
                ) -> typing.Tuple[StepTrace, dict, dict, dict, dict]:
    """Trace the full jitted train step (grads + optimizer update) against
    abstract state.  Returns (StepTrace, param shapes, param axes,
    optimizer-slot shapes, slot sharding axes)."""
    mesh = make_mesh(cfg) if mesh is None else mesh
    batch = abstract_batch(cfg)
    trainer = Trainer(cfg, mesh)
    micro = _micro_sds(batch, trainer.n_micro)
    params, axes = abstract_params(cfg, micro)
    trainer.axes = axes
    trainer.optimizer = Optimizer(cfg, axes)
    opt_state = jax.eval_shape(trainer.optimizer.init, params)
    state = TrainState(params, opt_state,
                       jax.ShapeDtypeStruct((), jnp.int32))
    step = trainer._make_step()
    with trace_compat(), mesh:
        # step_extra_args: telemetry-enabled configs take a grad_scale input
        traced = step.trace(state, batch, jax.random.key(0),
                            *trainer.step_extra_args())
    args_info = traced.args_info
    # args_info mirrors the call tree: ((state, batch, rng), {}) — the
    # TrainState subtree carries the donation bits the audit needs
    state_info = args_info[0][0]
    slot_axes = trainer.optimizer.slot_axis_names()
    # SPMD seeds, in TrainState's NamedTuple flatten order (params dict,
    # opt-slot dict-of-dicts, step scalar), then batch NTs, rng, extras
    in_axes: typing.List = _param_in_axes(params, axes)
    for name in sorted(opt_state):
        in_axes += _dict_axes(
            dict(opt_state[name]),
            lambda k, n=name: tuple(slot_axes.get(n, {}).get(k, ())))
    in_axes += [()]  # step counter
    in_axes += _dict_axes(batch, lambda k: tuple(batch[k].names))
    in_axes += [None]  # rng key
    in_axes += [() for _ in trainer.step_extra_args()]
    return (StepTrace("train", traced.jaxpr, mesh, args_info, state_info,
                      in_axes=_check_in_axes(traced.jaxpr, in_axes)),
            params, axes, dict(opt_state), slot_axes)


def trace_eval(cfg: Config, params, mesh=None, axes=None) -> StepTrace:
    """Trace the forward/eval walk (build -> total loss)."""
    mesh = make_mesh(cfg) if mesh is None else mesh
    batch = abstract_batch(cfg)

    def eval_fn(p, b):
        ctx = Ctx(cfg, params=p, train=False, rng=None, mesh=mesh)
        return build(ctx, b).loss

    with trace_compat(), mesh:
        jaxpr = jax.make_jaxpr(eval_fn)(params, batch)
    in_axes = (_param_in_axes(params, axes or {})
               + _dict_axes(batch, lambda k: tuple(batch[k].names)))
    return StepTrace("eval", jaxpr, mesh,
                     in_axes=_check_in_axes(jaxpr, in_axes))


def decode_traceable(cfg: Config) -> bool:
    from ..infer.kv_cache import cache_eligible
    return bool(cfg.use_language) and not cfg.use_video and cache_eligible(cfg)


def trace_prefill(cfg: Config, params, mesh=None, axes=None) -> StepTrace:
    """Trace the decode PREFILL: one full-length forward that writes every
    prompt position's K/V at once (the serving cold path — its activation
    peak, not the per-token step's, is what bounds prompt length)."""
    from ..infer.kv_cache import _decode_logits
    mesh = make_mesh(cfg) if mesh is None else mesh
    names = ("batch", "sequence", "language_token_patch")
    seq = cfg.sequence_length // cfg.token_patch_size
    toks = jax.ShapeDtypeStruct((1, seq, cfg.token_patch_size), jnp.int32)
    if cfg.pipeline_parallel > 1 and pipeline_params_stacked(cfg, params):
        from ..models import unstack_pipeline_params
        params = jax.eval_shape(
            lambda p: unstack_pipeline_params(cfg, p), params)

    def prefill(p, t):
        return _decode_logits(cfg, p, t, jnp.int32(0), {}, seq, names)

    with trace_compat():
        jaxpr = jax.make_jaxpr(prefill)(
            params, jnp.zeros(toks.shape, toks.dtype))
    in_axes = _param_in_axes(params, axes or {}) + [tuple(names)]
    return StepTrace("prefill", jaxpr, mesh,
                     in_axes=_check_in_axes(jaxpr, in_axes))


def trace_prefill_chunk(cfg: Config, params, mesh=None,
                        axes=None) -> StepTrace:
    """Trace ONE chunk-granular prefill forward: ``serve_prefill_chunk_rows``
    rows at a scalar running position against a populated cache — the
    executable the chunked admission path dispatches between decode steps
    (serve/engine.py::prefill_chunk_body).  Priced as its own step so the
    resource-budget audit stays honest when ``serve_prefill_chunk_tokens``
    is on: chunk activation peak scales with the chunk, not the prompt."""
    from ..infer.kv_cache import _decode_logits
    from ..serve.engine import prefill_chunk_rows
    mesh = make_mesh(cfg) if mesh is None else mesh
    names = ("batch", "sequence", "language_token_patch")
    seq = cfg.sequence_length // cfg.token_patch_size
    n_rows = prefill_chunk_rows(cfg)
    if n_rows <= 0:
        raise ValueError("trace_prefill_chunk needs "
                         "serve_prefill_chunk_tokens > 0")
    chunk = jax.ShapeDtypeStruct((1, n_rows, cfg.token_patch_size), jnp.int32)
    if cfg.pipeline_parallel > 1 and pipeline_params_stacked(cfg, params):
        from ..models import unstack_pipeline_params
        params = jax.eval_shape(
            lambda p: unstack_pipeline_params(cfg, p), params)

    def probe(p):
        return _decode_logits(
            cfg, p, jnp.zeros((1, 1, cfg.token_patch_size), jnp.int32),
            jnp.int32(0), {}, seq, names)[1]

    with trace_compat():
        caches = jax.eval_shape(probe, params)

        def chunk_step(p, t, c):
            return _decode_logits(cfg, p, t, jnp.int32(0), c, seq, names)

        jaxpr = jax.make_jaxpr(chunk_step)(
            params, jnp.zeros(chunk.shape, chunk.dtype), caches)
    in_axes = (_param_in_axes(params, axes or {}) + [tuple(names)]
               + [None] * len(jax.tree_util.tree_leaves(caches)))
    return StepTrace("prefill_chunk", jaxpr, mesh,
                     in_axes=_check_in_axes(jaxpr, in_axes))


def trace_decode(cfg: Config, params, mesh=None, axes=None) -> StepTrace:
    """Trace ONE incremental KV-cached decode step (the serving hot path)."""
    from ..infer.kv_cache import _decode_logits
    mesh = make_mesh(cfg) if mesh is None else mesh
    names = ("batch", "sequence", "language_token_patch")
    seq = cfg.sequence_length // cfg.token_patch_size
    row = jax.ShapeDtypeStruct((1, 1, cfg.token_patch_size), jnp.int32)
    # decode runs the flat per-depth layout (serve/interface.py unstacks)
    if cfg.pipeline_parallel > 1 and pipeline_params_stacked(cfg, params):
        from ..models import unstack_pipeline_params
        params = jax.eval_shape(
            lambda p: unstack_pipeline_params(cfg, p), params)

    def probe(p):
        return _decode_logits(cfg, p, jnp.zeros(row.shape, row.dtype),
                              jnp.int32(0), {}, seq, names)[1]

    with trace_compat():
        caches = jax.eval_shape(probe, params)

        def decode_step(p, r, c):
            return _decode_logits(cfg, p, r, jnp.int32(1), c, seq, names)

        jaxpr = jax.make_jaxpr(decode_step)(params, row, caches)
    in_axes = (_param_in_axes(params, axes or {}) + [tuple(names)]
               + [None] * len(jax.tree_util.tree_leaves(caches)))
    return StepTrace("decode", jaxpr, mesh,
                     in_axes=_check_in_axes(jaxpr, in_axes))


def trace_config(cfg: Config, config_name: str,
                 steps: typing.Sequence[str] = ("train", "decode"),
                 quiet: bool = False) -> ConfigTraces:
    """Trace the requested steps of one config, collecting per-step failures
    instead of aborting the whole audit.  ``quiet`` suppresses the local
    mesh's axis-fold warnings (the mesh searcher's internal traces would
    otherwise re-print the very warning its suggestion replaces)."""
    mesh = make_mesh(cfg, quiet=quiet)
    out: typing.Dict[str, StepTrace] = {}
    errors: typing.Dict[str, str] = {}
    params: typing.Dict[str, typing.Any] = {}
    axes: typing.Dict[str, typing.Tuple[str, ...]] = {}
    opt_shapes: typing.Dict[str, typing.Any] = {}
    slot_axes: typing.Dict[str, typing.Any] = {}
    if "train" in steps:
        try:
            out["train"], params, axes, opt_shapes, slot_axes = \
                trace_train(cfg, mesh)
        except Exception as e:  # surfaces as a trace-failure finding
            errors["train"] = f"{type(e).__name__}: {e}"
    if not params:
        try:
            trainer = Trainer(cfg, mesh)
            micro = _micro_sds(abstract_batch(cfg), trainer.n_micro)
            params, axes = abstract_params(cfg, micro)
        except Exception as e:
            errors.setdefault("params", f"{type(e).__name__}: {e}")
    if "eval" in steps and params:
        try:
            out["eval"] = trace_eval(cfg, params, mesh, axes=axes)
        except Exception as e:
            errors["eval"] = f"{type(e).__name__}: {e}"
    if "decode" in steps and params and decode_traceable(cfg):
        try:
            out["decode"] = trace_decode(cfg, params, mesh, axes=axes)
        except Exception as e:
            errors["decode"] = f"{type(e).__name__}: {e}"
    if "prefill" in steps and params and decode_traceable(cfg):
        try:
            out["prefill"] = trace_prefill(cfg, params, mesh, axes=axes)
        except Exception as e:
            errors["prefill"] = f"{type(e).__name__}: {e}"
    # the chunk executable rides along with "prefill" whenever the config
    # would actually compile it (serve_prefill_chunk_tokens > 0), and can
    # be requested explicitly; knob=0 configs trace exactly as before
    chunked = int(getattr(cfg, "serve_prefill_chunk_tokens", 0) or 0) > 0
    if (("prefill_chunk" in steps or ("prefill" in steps and chunked))
            and chunked and params and decode_traceable(cfg)):
        try:
            out["prefill_chunk"] = trace_prefill_chunk(cfg, params, mesh,
                                                       axes=axes)
        except Exception as e:
            errors["prefill_chunk"] = f"{type(e).__name__}: {e}"
    if params and not opt_shapes:
        # no successful train trace to reuse the slot shapes from
        try:
            opt = Optimizer(cfg, axes)
            opt_shapes = dict(jax.eval_shape(opt.init, params))
            slot_axes = opt.slot_axis_names()
        except Exception as e:
            errors.setdefault("opt_state", f"{type(e).__name__}: {e}")
    return ConfigTraces(config_name, cfg, mesh, out, axes, params, errors,
                        opt_state_shapes=dict(opt_shapes),
                        slot_axes=dict(slot_axes))
