"""graftcost: static memory & communication cost model over traced jaxprs.

The reference framework's whole value proposition is auditing pod-scale
configs without a pod (PAPER.md: abstract tracing, SimdMeshImpl layout known
ahead of time); graftcheck (PR 1) counts *which* collectives run — this
module predicts *how much*: per-device HBM, bytes per mesh axis, and whether
a workload is compute-, bandwidth-, or interconnect-bound, all from the
abstract traces, in seconds, on a CPU.

Per config x step (train / decode / prefill / prefill_chunk):

- **peak HBM per device** (analysis/memory.py): exact param + optimizer-slot
  bytes under the intended-mesh sharding, the input batch, KV-cache bytes
  (decode/prefill, via ``infer/kv_cache.py::cache_shapes``), and the
  activation/residual live-set peak from a linear scan over equation
  liveness (donated-buffer reuse credited; reversible/remat/quant savings
  fall out of the traced graph itself).
- **collective payload bytes per mesh axis**: every census-counted
  collective is *sized* (operand bytes, scan bodies multiplied by trip
  count) and attributed to the mesh axes it crosses, then priced with an
  alpha-beta estimate from the per-topology constants table
  (``homebrewnlp_tpu/devices.py``).  The IMPLICIT collectives GSPMD will
  insert — invisible in the traced jaxpr — are predicted by the sharding
  propagation pass (analysis/spmd.py) and priced identically
  (``StepResources.total_comm``).
- **roofline verdict**: ``mxu`` / ``hbm`` / ``ici`` from the static matmul
  flop count (``train/flops.py::jaxpr_flops``), an HBM-traffic proxy
  (2 x every value produced, sharded), and the alpha-beta ICI time.

Predictions are pinned by ratcheted goldens
(``analysis/goldens/resources/<config>.json``) through the graftcheck
``resource-budget`` rule: a config whose predicted peak grows past the
recorded budget — or exceeds its ``target_device``'s HBM capacity — fails
in CI before anything compiles.  bench.py records measured
``memory_stats()`` peaks next to these predictions (``prediction_error``)
so the constants table gets calibrated by every TPU round.
"""
from __future__ import annotations

import dataclasses
import json
import os
import typing

from ..devices import DeviceSpec, resolve_device
from ..train.flops import jaxpr_flops
from .findings import Finding
from .memory import (ScaledBytes, _sub_jaxprs, activation_divisor,
                     aval_nbytes, classify_shape, liveness_peak,
                     sharded_fraction)
from .trace import COLLECTIVE_PRIMS, ConfigTraces, StepTrace

GOLDENS_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "goldens")

#: growth past the recorded budget that fails the ratchet (regressions
#: smaller than this are absorbed as noise; shrinks below 1/RATIO ask for a
#: re-record via an info finding)
RATCHET_RATIO = 1.10
#: tolerated predicted-peak vs XLA temp-buffer-estimate ratio on the
#: CPU-compilable configs (recorded in each golden; tightened after TPU
#: calibration rounds)
XLA_RATIO = 2.0
#: device used for the roofline verdict when the config pins no
#: target_device (the bench fleet's device kind)
DEFAULT_VERDICT_DEVICE = "v5e"

#: fraction of the payload that actually crosses a link, per collective on
#: an n-way axis (ring algorithms): psum = reduce-scatter + all-gather
_CHUNK_FACTORS = {
    "psum": lambda n: 2.0 * (n - 1) / n,
    "all_gather": lambda n: (n - 1) / n,
    "reduce_scatter": lambda n: (n - 1) / n,
    "all_to_all": lambda n: (n - 1) / n,
    "ppermute": lambda n: 1.0,
    "pgather": lambda n: (n - 1) / n,
    "sharding_constraint": lambda n: (n - 1) / n,  # worst-case reshard
}


def _collective_axes(eqn) -> typing.Tuple[str, ...]:
    """Mesh axes one collective equation crosses."""
    ax = eqn.params.get("axis_name", eqn.params.get("axes"))
    if ax is None:
        spec = getattr(eqn.params.get("sharding"), "spec", None)
        if spec is None:
            return ()
        out = []
        for part in spec:
            for a in (part if isinstance(part, tuple) else (part,)):
                if a is not None:
                    out.append(a)
        return tuple(out)
    if isinstance(ax, str):
        return (ax,)
    return tuple(a for a in ax if isinstance(a, str))


@dataclasses.dataclass
class CommModel:
    """Per-mesh-axis communication totals for one step."""
    bytes_per_axis: typing.Dict[str, int]
    count_per_axis: typing.Dict[str, int]

    def times(self, imesh_shape: typing.Dict[str, int], spec: DeviceSpec
              ) -> typing.Dict[str, float]:
        """alpha-beta seconds per axis: beta uses the payload as already
        chunk-factored by the walk; alpha charges one launch per call times
        the ring hop count (an n-way ring collective is n-1 dependent
        hops)."""
        return {ax: (self.count_per_axis.get(ax, 0) * spec.alpha_s
                     * max(1, int(imesh_shape.get(ax, 2)) - 1)
                     + b / spec.ici_bw)
                for ax, b in self.bytes_per_axis.items()}


def _walk_comm_and_traffic(jaxpr, cfg, imesh, mult: int = 1,
                           acc=None) -> typing.Tuple[CommModel, float]:
    """One weighted walk collecting (a) per-axis collective payloads and
    (b) the HBM-traffic proxy: 2 x every equation-produced byte (written
    once, read about once), per-device via the activation divisor.  Scan
    bodies multiply by trip count — unlike the census, which counts static
    call sites, these figures are per-*execution* totals."""
    if acc is None:
        acc = (CommModel({}, {}), [0.0])
    comm, traffic = acc
    inner = jaxpr.jaxpr if hasattr(jaxpr, "jaxpr") else jaxpr
    for eqn in inner.eqns:
        fam = COLLECTIVE_PRIMS.get(eqn.primitive.name)
        if fam is not None:
            payload = sum(aval_nbytes(getattr(v, "aval", None))
                          for v in eqn.invars)
            for ax in _collective_axes(eqn):
                n = int(imesh.shape.get(ax, 1))
                if n <= 1:
                    continue
                moved = int(payload * mult
                            * _CHUNK_FACTORS.get(fam, lambda n: 1.0)(n))
                comm.bytes_per_axis[ax] = (
                    comm.bytes_per_axis.get(ax, 0) + moved)
                comm.count_per_axis[ax] = (
                    comm.count_per_axis.get(ax, 0) + mult)
        for v in eqn.outvars:
            aval = getattr(v, "aval", None)
            if aval is not None:
                div = activation_divisor(getattr(aval, "shape", ()),
                                         cfg, imesh)
                traffic[0] += 2.0 * mult * aval_nbytes(aval) / div
        sub_mult = mult
        if eqn.primitive.name == "scan":
            sub_mult = mult * int(eqn.params.get("length", 1) or 1)
        for item in _sub_jaxprs(eqn):
            _walk_comm_and_traffic(item, cfg, imesh, sub_mult,
                                   (comm, traffic))
    return comm, traffic[0]


# -- per-step resource prediction --------------------------------------------

@dataclasses.dataclass
class StepResources:
    """The prediction for one traced step (all byte figures per device on
    the intended mesh; ``scaled`` components power the graftcost sweep).
    ``comm`` holds the walked MANUAL collectives; ``implicit_comm`` the
    GSPMD-inserted ones the sharding propagation predicts
    (analysis/spmd.py) — :meth:`total_comm` is what pricing consumes."""
    hbm: typing.Dict[str, int]
    comm: CommModel
    flops_per_device: float
    hbm_traffic_bytes: float
    verdict: str
    verdict_device: str
    scaled: typing.Dict[str, typing.List[ScaledBytes]]
    implicit_comm: CommModel = dataclasses.field(
        default_factory=lambda: CommModel({}, {}))
    #: propagation failure captured for sheet/debug consumers; the
    #: implicit-collective rule reports the SAME failure as an error from
    #: its own (memoized, shared-cache) propagate() call — this field does
    #: not gate anything itself
    spmd_error: str = ""

    def total_comm(self) -> CommModel:
        """Manual + implicit collectives merged per mesh axis — the ONE
        communication total the roofline verdict, graftprof's
        reconciliation and the mesh-search objective all price."""
        merged = CommModel(dict(self.comm.bytes_per_axis),
                           dict(self.comm.count_per_axis))
        for ax, b in self.implicit_comm.bytes_per_axis.items():
            merged.bytes_per_axis[ax] = merged.bytes_per_axis.get(ax, 0) + b
        for ax, n in self.implicit_comm.count_per_axis.items():
            merged.count_per_axis[ax] = merged.count_per_axis.get(ax, 0) + n
        return merged

    def as_golden(self) -> dict:
        return {
            "hbm": {k: int(v) for k, v in sorted(self.hbm.items())},
            "collective_bytes_per_axis": {
                k: int(v) for k, v in sorted(self.comm.bytes_per_axis.items())},
            "implicit_collective_bytes_per_axis": {
                k: int(v) for k, v in
                sorted(self.implicit_comm.bytes_per_axis.items())},
            "flops_per_device": float(self.flops_per_device),
            "verdict": self.verdict,
        }


def _params_slots_bytes(traces: ConfigTraces, imesh
                        ) -> typing.Tuple[int, int, typing.List[ScaledBytes],
                                          typing.List[ScaledBytes]]:
    cfg = traces.cfg
    p_dev = 0
    p_scaled: typing.List[ScaledBytes] = []
    for name, sds in traces.param_shapes.items():
        frac = sharded_fraction(traces.param_axes.get(name, ()), imesh)
        b = aval_nbytes(sds) * frac
        p_dev += b
        p_scaled.append(classify_shape(sds.shape, b, cfg))
    s_dev = 0
    s_scaled: typing.List[ScaledBytes] = []
    for name, slots in traces.opt_state_shapes.items():
        axes = traces.slot_axes.get(name, {})
        for k, sds in slots.items():
            frac = sharded_fraction(axes.get(k, ()), imesh)
            b = aval_nbytes(sds) * frac
            s_dev += b
            s_scaled.append(classify_shape(sds.shape, b, cfg))
    return int(p_dev), int(s_dev), p_scaled, s_scaled


def _batch_bytes(cfg, imesh) -> typing.Tuple[int, typing.List[ScaledBytes]]:
    from .trace import abstract_batch
    total = 0
    scaled: typing.List[ScaledBytes] = []
    for t in abstract_batch(cfg).values():
        div = activation_divisor(t.x.shape, cfg, imesh)
        b = aval_nbytes(t.x) / div
        total += b
        scaled.append(classify_shape(t.x.shape, b, cfg))
    return int(total), scaled


def _kv_bytes(traces: ConfigTraces, imesh
              ) -> typing.Tuple[int, typing.List[ScaledBytes]]:
    """Per-device bytes of the serving KV POOL: the block allocator's
    geometry — ``pool_blocks x block_rows`` rows (infer/kv_cache.py, the
    continuous-batching engine's fixed-capacity pool) — times per-row
    cache bytes.  At the default serve knobs (one lane, whole-sequence
    blocks) this is exactly the decode trace's batch-of-1 monolithic
    cache; ``serve_max_batch``/``serve_kv_blocks`` scale it to the pool
    the engine actually allocates.  Scales linearly in batch x context by
    construction."""
    from ..infer.kv_cache import (block_rows, cache_eligible, cache_shapes,
                                  pool_blocks)
    cfg = traces.cfg
    params = traces.param_shapes
    if cfg.pipeline_parallel > 1:
        from ..models import pipeline_params_stacked, unstack_pipeline_params
        import jax
        if pipeline_params_stacked(cfg, params):
            params = jax.eval_shape(
                lambda p: unstack_pipeline_params(cfg, p), params)
    shapes = cache_shapes(cfg, params, 1)
    seq_rows = max(1, cfg.sequence_length // cfg.token_patch_size)
    # price the pool only where the batch engine actually allocates one
    # (serve_max_batch > 1 on an eligible stack — serve/engine.py's
    # use_batch_engine gate); the serialized path allocates the monolithic
    # batch-1 cache per call regardless of the pool knobs
    if getattr(cfg, "serve_max_batch", 1) > 1 and cache_eligible(cfg):
        pool_factor = pool_blocks(cfg) * block_rows(cfg) / seq_rows
    else:
        pool_factor = 1.0
    total = 0.0
    scaled: typing.List[ScaledBytes] = []
    for kv in shapes.values():
        for sds in kv:
            div = activation_divisor(sds.shape, cfg, imesh)
            b = aval_nbytes(sds) / div * pool_factor
            total += b
            c = classify_shape(sds.shape, b, cfg)
            # every cache row is per generated position and per sequence:
            # force the batch x context scaling even at the batch-1 anchor
            c.batch_exp = max(c.batch_exp, 1)
            c.seq_exp = max(c.seq_exp, 1)
            scaled.append(c)
    return int(total), scaled


def step_resources(traces: ConfigTraces, step: str, st: StepTrace, imesh,
                   device_kind: str = "") -> StepResources:
    cfg = traces.cfg
    p_dev, s_dev, p_scaled, s_scaled = _params_slots_bytes(traces, imesh)
    hbm: typing.Dict[str, int] = {"params": p_dev}
    scaled: typing.Dict[str, typing.List[ScaledBytes]] = {
        "params": p_scaled}
    if step == "train":
        hbm["opt_slots"] = s_dev
        scaled["opt_slots"] = s_scaled
    if step in ("train", "eval"):
        # eval consumes the same full batch as train (liveness never
        # counts jaxpr inputs — persistent state is accounted here)
        b, b_scaled = _batch_bytes(cfg, imesh)
        hbm["batch"] = b
        scaled["batch"] = b_scaled
    kv = 0
    if step in ("decode", "prefill", "prefill_chunk"):
        try:
            kv, kv_scaled = _kv_bytes(traces, imesh)
            scaled["kv_cache"] = kv_scaled
        except Exception:
            kv, scaled["kv_cache"] = 0, []
    hbm["kv_cache"] = kv
    # transient live set: donated train steps write the new state into the
    # donated input buffers; decode outputs are fresh allocations (the old
    # and the updated cache genuinely coexist — the serving loop does not
    # donate).  Prefill's outputs BEYOND the logits are the freshly written
    # caches themselves — already accounted (with forced batch x context
    # scaling) by the kv_cache term above, so counting them again as
    # liveness outputs would double the KV term and halve the sweep's
    # predicted max prompt length.
    if step in ("prefill", "prefill_chunk"):
        inner = st.jaxpr.jaxpr if hasattr(st.jaxpr, "jaxpr") else st.jaxpr
        live = liveness_peak(st.jaxpr, exclude_output_indices=set(
            range(1, len(inner.outvars))))
    else:
        live = liveness_peak(st.jaxpr, exclude_outputs=(step == "train"))
    act = 0.0
    act_scaled: typing.List[ScaledBytes] = []
    for aval in live.peak_live:
        div = activation_divisor(getattr(aval, "shape", ()), cfg, imesh)
        b = aval_nbytes(aval) / div
        act += b
        act_scaled.append(classify_shape(getattr(aval, "shape", ()), b, cfg))
    hbm["activation_peak"] = int(act)
    if step in ("decode", "prefill", "prefill_chunk"):
        # the decode/prefill traces run a batch of ONE (a batch dim of 1 is
        # invisible to shape classification), but every serving buffer is
        # per-request: impose linear batch scaling so the sweep can answer
        # "what serving batch fits"
        for c in act_scaled:
            c.batch_exp = max(c.batch_exp, 1)
    scaled["activation_peak"] = act_scaled
    hbm["peak"] = int(sum(v for k, v in hbm.items() if k != "peak"))

    comm, traffic = _walk_comm_and_traffic(st.jaxpr, cfg, imesh)
    # implicit collectives: what GSPMD will insert for this step under this
    # mesh (analysis/spmd.py) — priced exactly like the manual ones
    from .spmd import implicit_comm, propagate
    spmd_error = ""
    implicit = CommModel({}, {})
    try:
        prop = propagate(st, imesh)
        spmd_error = prop.error
        if prop.seeded and not prop.error:
            implicit = implicit_comm(prop, imesh)
    except Exception as e:  # surfaced by the implicit-collective rule
        spmd_error = f"{type(e).__name__}: {e}"
    n_dev = 1
    for v in imesh.shape.values():
        n_dev *= max(1, int(v))
    flops_dev = jaxpr_flops(st.jaxpr) / n_dev
    res = StepResources(hbm=hbm, comm=comm, flops_per_device=flops_dev,
                        hbm_traffic_bytes=traffic, verdict="unknown",
                        verdict_device="", scaled=scaled,
                        implicit_comm=implicit, spmd_error=spmd_error)
    res.verdict, res.verdict_device = _roofline(
        cfg, flops_dev, traffic, res.total_comm(), imesh, device_kind)
    return res


def static_step_times(flops_dev: float, traffic_bytes: float,
                      comm: CommModel,
                      imesh_shape: typing.Dict[str, int],
                      device_kind: str
                      ) -> typing.Optional[typing.Dict[str, typing.Any]]:
    """Static per-step seconds on one device kind: ``mxu`` (flops / peak),
    ``hbm`` (traffic proxy / bandwidth), ``ici`` (alpha-beta total, with
    the per-axis split under ``ici_per_axis``).  None for CPU/unknown
    kinds — no bandwidth claims are made there.  The ONE time model both
    the roofline verdict and graftprof's measured-vs-predicted
    reconciliation (obs/profile.py::reconcile) consume, so the two cannot
    disagree about what graftcost predicted."""
    from ..train.flops import peak_flops
    spec = resolve_device(device_kind)
    peak = peak_flops(device_kind)
    if spec is None or not peak:
        return None
    per_axis = comm.times(dict(imesh_shape), spec)
    return {"mxu": flops_dev / peak,
            "hbm": traffic_bytes / spec.hbm_bw,
            "ici": sum(per_axis.values()),
            "ici_per_axis": per_axis}


def step_static_times(res: "StepResources",
                      imesh_shape: typing.Dict[str, int],
                      device_kind: str
                      ) -> typing.Optional[typing.Dict[str, typing.Any]]:
    """:func:`static_step_times` over an already-built prediction — the
    communication term is :meth:`StepResources.total_comm` (manual PLUS
    GSPMD-implicit collectives)."""
    return static_step_times(res.flops_per_device, res.hbm_traffic_bytes,
                             res.total_comm(), imesh_shape, device_kind)


def _roofline(cfg, flops_dev: float, traffic: float, comm: CommModel,
              imesh, device_kind: str = ""
              ) -> typing.Tuple[str, str]:
    """(verdict, device kind used).  MXU vs HBM vs ICI by which static time
    estimate dominates on the target (or default-verdict) device."""
    kind = device_kind or getattr(cfg, "target_device", "") \
        or DEFAULT_VERDICT_DEVICE
    times = static_step_times(flops_dev, traffic, comm, dict(imesh.shape),
                              kind)
    if times is None:
        return "unknown", kind
    ranked = {k: times[k] for k in ("mxu", "hbm", "ici")}
    return max(ranked, key=ranked.get), kind


def serve_capacity_ceiling() -> typing.Dict[str, typing.Any]:
    """Static flops ceiling for the serving fleet on THIS process's
    devices: ``peak_flops_per_s`` is the cost model's per-device peak
    (``train.flops.peak_flops``) times the local device count, or None
    on CPU/unknown kinds where no throughput claim is made.  The usage
    meter divides metered flops/s by this ceiling to report
    ``capacity_utilization`` on ``/healthz`` — one number, priced from
    the same table as the roofline verdicts, so capacity reports and
    graftcost predictions cannot disagree."""
    import jax
    from ..train.flops import peak_flops
    devices = jax.devices()
    kind = getattr(devices[0], "device_kind", "") if devices else ""
    peak = peak_flops(kind)
    return {"device_kind": kind,
            "n_devices": len(devices),
            "peak_flops_per_s": (peak * len(devices)) if peak else None}


def config_resources(traces: ConfigTraces, device_kind: str = ""
                     ) -> typing.Dict[str, StepResources]:
    from .graph_rules import intended_mesh
    imesh = intended_mesh(traces.cfg)
    return {name: step_resources(traces, name, st, imesh, device_kind)
            for name, st in sorted(traces.steps.items())}


# -- ratcheted goldens + the resource-budget rule ----------------------------

def resources_golden_path(config_name: str) -> str:
    return os.path.join(GOLDENS_DIR, "resources", config_name + ".json")


def _loc(traces: ConfigTraces, step: str) -> str:
    return f"configs/{traces.config_name}.json[{step}]"


def format_bytes(b: float, width: int = 0) -> str:
    """Human-readable bytes — the ONE renderer rule messages and the
    graftcost sheet share (``width`` right-aligns for the table)."""
    w = str(width) if width else ""
    pad = "  " if width else ""
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(b) < 1024 or unit == "TiB":
            if unit == "B":
                return f"{int(b):{w}d} B{pad}"
            return f"{b:{w}.2f} {unit}"
        b /= 1024
    return f"{b:.2f} TiB"


def _fmt(b: float) -> str:
    return format_bytes(b)


def check_resource_budget(traces: ConfigTraces,
                          update_goldens: bool = False
                          ) -> typing.List[Finding]:
    """The graftcheck rule: predicted resources vs the ratcheted golden,
    plus the OOM-before-compile gate against ``cfg.target_device``."""
    findings: typing.List[Finding] = []
    try:
        actual = config_resources(traces)
    except Exception as e:  # a cost-model crash must name itself, not pass
        return [Finding("resource-budget", "error",
                        _loc(traces, "*"),
                        f"cost model failed: {type(e).__name__}: {e}")]
    path = resources_golden_path(traces.config_name)
    target = str(getattr(traces.cfg, "target_device", "") or "")
    spec = resolve_device(target) if target else None

    if not target and int(getattr(traces.cfg, "tpu_size", 1)) > 1:
        # a multi-device config without a target device runs with the
        # OOM-before-compile gate DEAD (exactly how all nine committed
        # goldens shipped with target_device: "") — surface it
        findings.append(Finding(
            "resource-budget", "warning", _loc(traces, "*"),
            f"tpu_size={traces.cfg.tpu_size} but target_device is empty — "
            f"the OOM-before-compile gate cannot run and the roofline/mesh "
            f"search falls back to {DEFAULT_VERDICT_DEVICE!r}; set "
            f"target_device to the fleet's device kind "
            f"(homebrewnlp_tpu/devices.py)"))

    # OOM-before-compile gate: independent of the golden, so an inflated
    # context/batch fails even on a freshly re-recorded budget
    if spec is not None:
        for step, res in actual.items():
            if res.hbm["peak"] > spec.hbm_bytes:
                findings.append(Finding(
                    "resource-budget", "error", _loc(traces, step),
                    f"predicted peak HBM {_fmt(res.hbm['peak'])} exceeds "
                    f"{target}'s {_fmt(spec.hbm_bytes)} per chip — OOM "
                    f"before compile (params {_fmt(res.hbm['params'])}, "
                    f"slots {_fmt(res.hbm.get('opt_slots', 0))}, "
                    f"activations {_fmt(res.hbm['activation_peak'])}, "
                    f"kv {_fmt(res.hbm['kv_cache'])}); shrink batch/context "
                    f"or re-shard"))

    if update_goldens:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        import jax
        merged = {s: r.as_golden() for s, r in actual.items()}
        if os.path.exists(path):
            with open(path) as f:
                for step, budget in json.load(f).get("steps", {}).items():
                    merged.setdefault(step, budget)
        with open(path, "w") as f:
            json.dump({"config": traces.config_name,
                       "jax": jax.__version__,
                       "target_device": target,
                       "intended_mesh": {k: int(v) for k, v in
                                         _imesh_shape(traces).items()},
                       "tolerance": {"ratchet": RATCHET_RATIO,
                                     "xla": XLA_RATIO},
                       "steps": merged}, f, indent=2, sort_keys=True)
            f.write("\n")
        findings.append(Finding(
            "resource-budget", "info", path,
            f"resources golden updated ({', '.join(actual) or 'no steps'})"))
        return findings

    if not os.path.exists(path):
        findings.append(Finding(
            "resource-budget", "error", _loc(traces, "*"),
            f"no resources golden at {os.path.relpath(path)}; run "
            f"`python tools/graftcheck.py --config configs/"
            f"{traces.config_name}.json --update-goldens`"))
        return findings
    with open(path) as f:
        golden = json.load(f)
    ratchet = float(golden.get("tolerance", {}).get("ratchet", RATCHET_RATIO))
    gsteps = golden.get("steps", {})
    for step in sorted(set(actual) | set(gsteps)):
        if step not in actual:
            findings.append(Finding(
                "resource-budget", "warning", _loc(traces, step),
                "step present in resources golden but not traced this run "
                f"({traces.errors.get(step, 'step skipped')})"))
            continue
        if step not in gsteps:
            findings.append(Finding(
                "resource-budget", "warning", _loc(traces, step),
                "step traced but not pinned by the resources golden; record "
                "it with --update-goldens to gate it"))
            continue
        got, want = actual[step].as_golden(), gsteps[step]
        g_peak, w_peak = got["hbm"]["peak"], want["hbm"].get("peak", 0)
        if w_peak and g_peak > w_peak * ratchet:
            findings.append(Finding(
                "resource-budget", "error", _loc(traces, step),
                f"predicted peak HBM {_fmt(g_peak)} regressed past the "
                f"golden budget {_fmt(w_peak)} (x{g_peak / w_peak:.2f} > "
                f"ratchet x{ratchet}) — if intended, re-record with "
                f"--update-goldens"))
        elif w_peak and g_peak * ratchet < w_peak:
            findings.append(Finding(
                "resource-budget", "info", _loc(traces, step),
                f"predicted peak HBM improved {_fmt(w_peak)} -> "
                f"{_fmt(g_peak)}; re-record with --update-goldens to "
                f"ratchet the gain"))
        ga = got["collective_bytes_per_axis"]
        wa = want.get("collective_bytes_per_axis", {})
        for ax in sorted(set(ga) | set(wa)):
            g, w = ga.get(ax, 0), wa.get(ax, 0)
            if g > max(w, 1) * ratchet and g - w > 1024:
                findings.append(Finding(
                    "resource-budget", "error", _loc(traces, step),
                    f"collective payload on mesh axis {ax!r} grew "
                    f"{_fmt(w)} -> {_fmt(g)} past the ratchet — an "
                    f"unplanned reshard or a fatter collective; if "
                    f"intended, re-record with --update-goldens"))
        if got["verdict"] != want.get("verdict", got["verdict"]):
            findings.append(Finding(
                "resource-budget", "warning", _loc(traces, step),
                f"roofline verdict changed {want.get('verdict')!r} -> "
                f"{got['verdict']!r} on {actual[step].verdict_device} — the "
                f"workload's bottleneck moved; re-record if intended"))
    return findings


def _imesh_shape(traces: ConfigTraces) -> typing.Dict[str, int]:
    from .graph_rules import intended_mesh
    return dict(intended_mesh(traces.cfg).shape)


# -- sweep model (tools/graftcost.py) ----------------------------------------

@dataclasses.dataclass
class SweepModel:
    """Scaling model built from ONE traced anchor: every HBM component of
    every step, classified by batch/sequence exponents, so sweeping context
    1k -> 128k is arithmetic instead of 8 more traces.  The anchor ambiguity
    (batch == seq) is surfaced via ``ambiguous``.  The train step anchors at
    ``train_batch_size``; decode/prefill anchor at the serving batch of 1
    their traces run."""
    config_name: str
    anchor_batch: int
    anchor_seq: int
    steps: typing.Dict[str, StepResources]
    ambiguous: bool

    def step_anchor_batch(self, step: str) -> int:
        return self.anchor_batch if step == "train" else 1

    def peak_at(self, step: str, batch: typing.Optional[int] = None,
                context: typing.Optional[int] = None
                ) -> typing.Dict[str, float]:
        """Per-component HBM bytes at a scaled (batch, context) point."""
        res = self.steps[step]
        br = (batch / self.step_anchor_batch(step)) if batch else 1.0
        sr = (context / self.anchor_seq) if context else 1.0
        out = {k: sum(c.at(br, sr) for c in comps)
               for k, comps in res.scaled.items()}
        out["peak"] = sum(out.values())
        return out


def build_sweep_model(traces: ConfigTraces) -> SweepModel:
    cfg = traces.cfg
    return SweepModel(
        config_name=traces.config_name,
        anchor_batch=cfg.train_batch_size,
        anchor_seq=cfg.sequence_length,
        steps=config_resources(traces),
        ambiguous=(cfg.train_batch_size == cfg.sequence_length))


def first_exceeding(model: SweepModel, step: str, spec: DeviceSpec,
                    points: typing.Sequence[int], key: str = "context",
                    batch: typing.Optional[int] = None
                    ) -> typing.Optional[int]:
    """Smallest swept ``key`` value (``"context"`` or ``"batch"``) whose
    predicted peak exceeds ``spec``'s HBM (None when every point fits).
    The single source of the fits/OOM boundary — tools/graftcost.py and
    the tests both call it."""
    for v in sorted(points):
        kw = {"batch": batch, "context": v} if key == "context" \
            else {"batch": v}
        if model.peak_at(step, **kw)["peak"] > spec.hbm_bytes:
            return v
    return None


def first_context_exceeding(model: SweepModel, step: str, spec: DeviceSpec,
                            contexts: typing.Sequence[int],
                            batch: typing.Optional[int] = None
                            ) -> typing.Optional[int]:
    """Smallest swept context whose predicted peak exceeds ``spec``'s HBM
    (None when every point fits) — the long-context planning entry point."""
    return first_exceeding(model, step, spec, contexts, "context", batch)
