"""graftspmd: static sharding propagation — census the *implicit* collectives.

The reference framework decides its communication layout statically
(SimdMeshImpl picks collectives from named-dim layouts at graph-build
time); the JAX port hands that decision to GSPMD, which inserts
all-reduces/all-gathers *silently* at compile time.  The census
(graph_rules.py) counts the manual collectives the traced jaxpr contains;
this module predicts the implicit ones the partitioner will add:

1. **Seed** every jaxpr input with the PartitionSpec the intended mesh
   would apply (``parallel/sharding.py::spec_for`` over the axis-name
   metadata the trace harness records per invar, ``StepTrace.in_axes``).
2. **Propagate** specs equation by equation — elementwise ops merge
   operand specs, ``dot_general``/reductions over a sharded dimension
   leave partial sums (⇒ implicit all-reduce / reduce-scatter), structural
   ops (transpose/reshape/broadcast/gather) remap dims, scan bodies
   multiply by trip count, ``shard_map`` regions are opaque (manual —
   the explicit census already prices them).
3. **Emit** an implicit-collective census — kind x mesh axis x payload
   bytes — plus a lint finding for every equation whose operands carry
   *conflicting* shardings (the classic accidental full replication).

Propagation is deliberately conservative: an input or intermediate whose
sharding cannot be tracked becomes UNKNOWN and stops charging (it can
under-count, never invent collectives), which is why the census is
validated against ground truth: on CPU-compilable configs
:func:`validate_hlo` lowers + compiles the real train step under the real
shardings and compares the predicted census with the collectives present
in the partitioned HLO text, within the documented tolerance
(:data:`HLO_TOLERANCE`).

Consumers: ``cost_model.step_resources`` (implicit bytes priced into the
roofline/mesh-search objective via ``StepResources.total_comm``), the
ratcheted ``implicit-collective`` graph rule (goldens under
``analysis/goldens/spmd/``), and ``tools/graftspmd.py``.
"""
from __future__ import annotations

import dataclasses
import json
import os
import re
import typing

from .findings import Finding
from .trace import ConfigTraces, StepTrace, eqn_location

GOLDENS_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "goldens")

#: growth past the recorded implicit-byte budget that fails the ratchet
RATCHET_RATIO = 1.10

#: HLO cross-validation tolerance: per-device collective payload bytes in
#: the partitioned HLO may differ from the prediction by at most this
#: factor (XLA's all-reduce combiner merges ops and GSPMD may choose
#: reduce-scatter/all-gather decompositions; bytes are conserved far
#: better than op counts), with :data:`HLO_BYTES_SLACK` absolute slack for
#: scalar bookkeeping collectives the propagation does not model.
HLO_TOLERANCE = 2.0
HLO_BYTES_SLACK = 64 * 1024
#: op-count slack: |predicted - lowered| <= max(HLO_COUNT_SLACK,
#: 0.5 * max(predicted, lowered))
HLO_COUNT_SLACK = 4

#: collective op spellings in optimized/partitioned HLO text.  The census
#: itself emits two families: ``psum`` (partial-sum contraction/reduction
#: over a sharded dim — lowered as all-reduce or reduce-scatter) and
#: ``all_gather`` (operand-sharding mismatch / gather-the-smaller-operand
#: strategy).
HLO_COLLECTIVE_OPS = ("all-reduce", "all-gather", "reduce-scatter",
                      "collective-permute", "all-to-all")

# a spec is a tuple of (mesh axis | None) per dim; None (the whole spec)
# means UNKNOWN — propagation follows the known side and never charges
Spec = typing.Optional[typing.Tuple[typing.Optional[str], ...]]

_ELEMENTWISE = frozenset((
    "add", "sub", "mul", "div", "neg", "max", "min", "rem", "pow",
    "integer_pow", "exp", "log", "log1p", "expm1", "tanh", "logistic",
    "sqrt", "rsqrt", "cbrt", "abs", "sign", "floor", "ceil", "round",
    "erf", "erf_inv", "erfc", "sin", "cos", "clamp", "select_n",
    "and", "or", "xor", "not", "eq", "ne", "lt", "le", "gt", "ge",
    "is_finite", "square", "atan2", "nextafter", "shift_left",
    "shift_right_logical", "shift_right_arithmetic", "real", "imag",
    "stop_gradient", "copy", "reduce_precision", "add_any",
))

_IDENTITY = frozenset(("convert_element_type", "bitcast_convert_type",
                       "copy", "stop_gradient", "optimization_barrier"))

_REDUCE_PRIMS = frozenset(("reduce_sum", "reduce_prod", "reduce_max",
                           "reduce_min", "reduce_and", "reduce_or",
                           "reduce_xor", "argmax", "argmin",
                           "reduce_logsumexp"))

_OPAQUE_CALLS = frozenset(("shard_map",))


def _nbytes(aval) -> int:
    from .memory import aval_nbytes
    return aval_nbytes(aval)


@dataclasses.dataclass
class ChargeOption:
    """One way the partitioner could realize an implicit collective:
    ``full_bytes`` is the unsharded value moved, ``shard_axes`` the mesh
    axes sharding it (the per-device divide)."""
    family: str
    full_bytes: int
    shard_axes: typing.Tuple[str, ...]


@dataclasses.dataclass
class ImplicitRecord:
    """One implicit collective, mesh-size-symbolically: ``axis`` is the
    mesh axis it crosses; ``options`` the partitioner's candidate
    lowerings (e.g. all-reduce the partial-sum output vs all-gather the
    smaller operand and contract locally) — census picks the cheapest, as
    GSPMD's own sharding strategies do.  Sizes are applied at census time
    so one propagation serves every candidate mesh with the same >1-axis
    mask (the mesh searcher's loop)."""
    axis: str
    options: typing.Tuple[ChargeOption, ...]
    count: int
    location: str


@dataclasses.dataclass
class Conflict:
    location: str
    prim: str
    detail: str


@dataclasses.dataclass
class SpmdResult:
    step: str
    mask: typing.FrozenSet[str]
    records: typing.List[ImplicitRecord]
    conflicts: typing.List[Conflict]
    seeded: bool  # False: no usable in_axes metadata — nothing charged
    error: str = ""


class _MaskMesh:
    """spec_for-compatible stand-in whose axes are 2 when active in the
    mask, 1 otherwise — propagation only cares *which* axes shard."""

    def __init__(self, mask: typing.FrozenSet[str]):
        from ..parallel.mesh import MESH_AXES
        self.shape = {a: (2 if a in mask else 1) for a in MESH_AXES}
        self.axis_names = tuple(self.shape)


def _dedup(parts: typing.List[typing.Optional[str]]) -> Spec:
    """A mesh axis may shard at most one dim; first occurrence wins (the
    same contract as spec_for)."""
    seen: typing.Set[str] = set()
    out: typing.List[typing.Optional[str]] = []
    for p in parts:
        if p is not None and p in seen:
            p = None
        elif p is not None:
            seen.add(p)
        out.append(p)
    return tuple(out)


def _spec_axes(spec: Spec) -> typing.Tuple[str, ...]:
    return tuple(p for p in (spec or ()) if p is not None)


class _Propagator:
    """One forward walk over a (Closed)Jaxpr tree under a fixed axis mask."""

    def __init__(self, mask: typing.FrozenSet[str]):
        self.mask = mask
        self.records: typing.List[ImplicitRecord] = []
        self.conflicts: typing.List[Conflict] = []

    # -- env helpers ---------------------------------------------------------
    def read(self, env: dict, v) -> Spec:
        if hasattr(v, "val"):  # Literal
            rank = len(getattr(getattr(v, "aval", None), "shape", ()) or ())
            return (None,) * rank
        return env.get(v)

    def write(self, env: dict, v, spec: Spec) -> None:
        aval = getattr(v, "aval", None)
        if aval is None:
            return
        rank = len(getattr(aval, "shape", ()) or ())
        if spec is not None and len(spec) != rank:
            spec = None  # shape/spec drift: degrade to unknown, never lie
        env[v] = spec

    # -- charging ------------------------------------------------------------
    def charge(self, family: str, axis: str, full_bytes: int,
               shard_axes: typing.Sequence[str], mult: int, eqn,
               alternatives: typing.Sequence[ChargeOption] = ()) -> None:
        if axis not in self.mask or full_bytes <= 0 or mult <= 0:
            return
        opts = (ChargeOption(family, int(full_bytes),
                             tuple(a for a in shard_axes if a != axis)),)
        opts += tuple(ChargeOption(o.family, int(o.full_bytes),
                                   tuple(a for a in o.shard_axes
                                         if a != axis))
                      for o in alternatives if o.full_bytes > 0)
        self.records.append(ImplicitRecord(
            axis=axis, options=opts, count=int(mult),
            location=eqn_location(eqn)))

    def conflict(self, eqn, detail: str) -> None:
        self.conflicts.append(Conflict(eqn_location(eqn),
                                       eqn.primitive.name, detail))

    def _merge_dim(self, a: typing.Optional[str], b: typing.Optional[str],
                   eqn, dim: int, other_aval, other_spec: Spec,
                   mult: int) -> typing.Optional[str]:
        """Merge one dim's sharding across two operands.  Sharded beats
        replicated (GSPMD slices the replicated side locally — no
        movement); two DIFFERENT axes conflict: the partitioner reshards
        (typically by gathering one side to replicated), charged as an
        implicit all_gather plus a lint conflict."""
        if a is None or a == b:
            return b if a is None else a
        if b is None:
            return a
        self.conflict(eqn, f"dim {dim} sharded {a!r} vs {b!r} — GSPMD "
                           f"reshards (typically gathers one side to "
                           f"replicated)")
        self.charge("all_gather", b, _nbytes(other_aval),
                    _spec_axes(other_spec), mult, eqn)
        return a

    def merge_specs(self, eqn, vars_specs, mult: int) -> Spec:
        """Elementwise merge: operands of the output's rank merge per dim;
        lower-rank operands (scalars and other already-broadcast values)
        are replicated riders and neither constrain nor conflict."""
        rank = len(getattr(eqn.outvars[0].aval, "shape", ()) or ())
        known = [(v, s) for v, s in vars_specs
                 if s is not None and len(s) == rank]
        if not known:
            return None if any(s is None for _, s in vars_specs) \
                else (None,) * rank
        parts: typing.List[typing.Optional[str]] = [None] * rank
        for v, s in known:
            for d in range(rank):
                parts[d] = self._merge_dim(parts[d], s[d], eqn, d,
                                           getattr(v, "aval", None), s, mult)
        return _dedup(parts)

    # -- the walk ------------------------------------------------------------
    def run(self, jaxpr, in_specs: typing.Sequence[Spec], mult: int = 1
            ) -> typing.List[Spec]:
        """Propagate through one (Closed)Jaxpr; returns outvar specs."""
        inner = jaxpr.jaxpr if hasattr(jaxpr, "jaxpr") else jaxpr
        env: dict = {}
        for v, s in zip(inner.invars, in_specs):
            self.write(env, v, s)
        for v in getattr(inner, "constvars", ()):
            aval = getattr(v, "aval", None)
            rank = len(getattr(aval, "shape", ()) or ())
            self.write(env, v, (None,) * rank)
        for eqn in inner.eqns:
            self.eqn(env, eqn, mult)
        return [self.read(env, v) for v in inner.outvars]

    def eqn(self, env: dict, eqn, mult: int) -> None:
        name = eqn.primitive.name
        specs = [self.read(env, v) for v in eqn.invars]
        handler = getattr(self, "_h_" + name.replace("-", "_"), None)
        if handler is not None:
            outs = handler(env, eqn, specs, mult)
        elif name in _OPAQUE_CALLS:
            outs = [None] * len(eqn.outvars)  # manual region: census owns it
        elif name in _REDUCE_PRIMS:
            outs = self._reduce(eqn, specs, mult)
        elif name in _IDENTITY:
            outs = [specs[0]]
        elif name in _ELEMENTWISE:
            outs = [self.merge_specs(eqn, list(zip(eqn.invars, specs)), mult)]
        else:
            outs = self._generic(eqn, specs, mult)
        for v, s in zip(eqn.outvars, list(outs) + [None] * len(eqn.outvars)):
            self.write(env, v, s)

    # -- structural handlers -------------------------------------------------
    def _h_transpose(self, env, eqn, specs, mult):
        s = specs[0]
        if s is None:
            return [None]
        perm = eqn.params["permutation"]
        return [tuple(s[p] for p in perm)]

    def _h_reshape(self, env, eqn, specs, mult):
        s = specs[0]
        if s is None or not _spec_axes(s):
            out_rank = len(eqn.outvars[0].aval.shape)
            return [(None,) * out_rank]
        in_shape = [int(d) for d in eqn.invars[0].aval.shape]
        out_shape = [int(d) for d in eqn.outvars[0].aval.shape]
        # map sharded input dims to same-size output dims left-to-right;
        # a sharded dim that merges/splits degrades to replicated (GSPMD
        # usually keeps these local — halo effects are noise at this level)
        parts: typing.List[typing.Optional[str]] = [None] * len(out_shape)
        j = 0
        for i, d in enumerate(in_shape):
            if s[i] is None:
                continue
            while j < len(out_shape) and out_shape[j] != d:
                j += 1
            if j < len(out_shape):
                parts[j] = s[i]
                j += 1
        return [_dedup(parts)]

    def _h_squeeze(self, env, eqn, specs, mult):
        s = specs[0]
        if s is None:
            return [None]
        dims = set(eqn.params["dimensions"])
        return [tuple(p for d, p in enumerate(s) if d not in dims)]

    def _h_expand_dims(self, env, eqn, specs, mult):
        s = specs[0]
        if s is None:
            return [None]
        dims = sorted(eqn.params["dimensions"])
        out = list(s)
        for d in dims:
            out.insert(d, None)
        return [tuple(out)]

    def _h_broadcast_in_dim(self, env, eqn, specs, mult):
        s = specs[0]
        out_shape = eqn.outvars[0].aval.shape
        parts: typing.List[typing.Optional[str]] = [None] * len(out_shape)
        if s is not None:
            in_shape = eqn.invars[0].aval.shape
            for i, od in enumerate(eqn.params["broadcast_dimensions"]):
                if int(in_shape[i]) == int(out_shape[od]):
                    parts[od] = s[i]
        return [_dedup(parts)]

    def _h_slice(self, env, eqn, specs, mult):
        return [self._dim_preserving(eqn, specs[0])]

    def _h_dynamic_slice(self, env, eqn, specs, mult):
        return [self._dim_preserving(eqn, specs[0])]

    def _h_pad(self, env, eqn, specs, mult):
        return [self._dim_preserving(eqn, specs[0])]

    def _h_rev(self, env, eqn, specs, mult):
        s = specs[0]
        if s is None:
            return [None]
        dims = set(eqn.params["dimensions"])
        return [tuple(None if d in dims else p for d, p in enumerate(s))]

    def _dim_preserving(self, eqn, s: Spec) -> Spec:
        """Keep sharding only on dims whose size is unchanged."""
        if s is None:
            return None
        in_shape = eqn.invars[0].aval.shape
        out_shape = eqn.outvars[0].aval.shape
        if len(in_shape) != len(out_shape):
            return None
        return tuple(p if int(a) == int(b) else None
                     for p, a, b in zip(s, in_shape, out_shape))

    def _h_dynamic_update_slice(self, env, eqn, specs, mult):
        return [specs[0]]

    def _h_scatter(self, env, eqn, specs, mult):
        return [specs[0]]

    def _h_scatter_add(self, env, eqn, specs, mult):
        """The embedding-gradient shape: data-sharded updates scatter-added
        into a replicated table — every shard holds a partial table grad,
        so GSPMD must all-reduce the OPERAND-sized result over each axis
        the updates/indices carry but the table does not."""
        out_axes = _spec_axes(specs[0])
        charged: typing.Set[str] = set()
        for i, s in enumerate(specs[1:], start=1):
            for axis in _spec_axes(s):
                if axis not in out_axes and axis not in charged:
                    charged.add(axis)
                    # alternative: gather the sharded updates and scatter
                    # locally (cheaper when the table dwarfs the updates)
                    alt = (ChargeOption("all_gather",
                                        _nbytes(eqn.invars[i].aval),
                                        _spec_axes(s)),)
                    self.charge("psum", axis, _nbytes(eqn.outvars[0].aval),
                                out_axes, mult, eqn, alternatives=alt)
        return [specs[0]]

    def _h_concatenate(self, env, eqn, specs, mult):
        known = [s for s in specs if s is not None]
        if len(known) != len(specs) or len(set(known)) != 1:
            return [None]
        dim = int(eqn.params["dimension"])
        s = list(known[0])
        if dim < len(s):
            s[dim] = None  # the concatenated dim's sharding does not survive
        return [tuple(s)]

    def _h_gather(self, env, eqn, specs, mult):
        """Embedding-lookup shape: a gather from a replicated/unknown table
        with KNOWN index sharding carries the index spec onto the output's
        gather-batch dims (offset dims — operand slices — replicate)."""
        idx_spec = specs[1] if len(specs) > 1 else None
        out_rank = len(eqn.outvars[0].aval.shape)
        if idx_spec is None:
            return [None]
        dn = eqn.params.get("dimension_numbers")
        offset = set(getattr(dn, "offset_dims", ()) or ())
        # index dims feeding the output, in order (drop the trailing
        # index-vector dim)
        idx_parts = list(idx_spec[:-1]) if len(idx_spec) else []
        parts: typing.List[typing.Optional[str]] = []
        k = 0
        for d in range(out_rank):
            if d in offset:
                parts.append(None)
            else:
                parts.append(idx_parts[k] if k < len(idx_parts) else None)
                k += 1
        return [_dedup(parts)]

    def _h_sharding_constraint(self, env, eqn, specs, mult):
        """A constraint pins dims it names; dims it leaves open KEEP the
        propagated sharding.  The annotation was baked at trace time under
        the LOCAL mesh (axes of size 1 are omitted by spec_for), so on the
        intended/candidate mesh it systematically under-specifies —
        overwriting would erase the very seeds the analysis planted."""
        spec = getattr(eqn.params.get("sharding"), "spec", None)
        rank = len(eqn.outvars[0].aval.shape)
        incoming = specs[0] if specs and specs[0] is not None \
            else (None,) * rank
        if spec is None:
            return [specs[0]]
        parts: typing.List[typing.Optional[str]] = []
        for d in range(rank):
            part = spec[d] if d < len(spec) else None
            axes = [a for a in (part if isinstance(part, tuple) else (part,))
                    if a is not None]
            parts.append(axes[0] if axes else incoming[d])
        return [_dedup(parts)]

    # -- compute handlers ----------------------------------------------------
    def _reduce(self, eqn, specs, mult):
        s = specs[0]
        if s is None:
            return [None] * len(eqn.outvars)
        dims = set(int(d) for d in (eqn.params.get("axes") or ()))
        out = tuple(p for d, p in enumerate(s) if d not in dims)
        out_aval = eqn.outvars[0].aval
        for d in sorted(dims):
            if d < len(s) and s[d] is not None:
                # cross-shard reduction: partial results per shard must be
                # combined — an implicit all-reduce of the OUTPUT (max/min
                # move the same payload as sums)
                self.charge("psum", s[d], _nbytes(out_aval),
                            _spec_axes(out), mult, eqn)
        return [out] * len(eqn.outvars)

    def _h_dot_general(self, env, eqn, specs, mult):
        ls, rs = specs[0], specs[1]
        (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
        l_rank = len(eqn.invars[0].aval.shape)
        r_rank = len(eqn.invars[1].aval.shape)
        l = ls if ls is not None else (None,) * l_rank
        r = rs if rs is not None else (None,) * r_rank
        parts: typing.List[typing.Optional[str]] = []
        for i, (dl, dr) in enumerate(zip(lb, rb)):
            parts.append(self._merge_dim(
                l[dl], r[dr], eqn, dl, eqn.invars[1].aval, rs, mult))
        lfree = [d for d in range(l_rank) if d not in lc and d not in lb]
        rfree = [d for d in range(r_rank) if d not in rc and d not in rb]
        parts += [l[d] for d in lfree]
        parts += [r[d] for d in rfree]
        out = _dedup(parts)
        out_axes = _spec_axes(out)
        contracted: typing.List[str] = []
        for d in lc:
            if l[d] is not None and l[d] not in contracted:
                contracted.append(l[d])
        for d in rc:
            if r[d] is not None and r[d] not in contracted:
                contracted.append(r[d])
        out_aval = eqn.outvars[0].aval
        for axis in contracted:
            if axis in out_axes:
                continue  # the axis re-shards an output dim: local reuse
            # contraction over a sharded dim: GSPMD either all-reduces the
            # partial-sum output (reduce-scatter when the consumer wants
            # it sharded) or ALL-GATHERS the smaller operand over that
            # axis and contracts locally — offer both, census picks the
            # cheaper, exactly like the partitioner's strategy choice
            # (weight-stationary grad all-reduce vs gathering a weight
            # that is tiny next to a batch-sized activation)
            alts = []
            for i in (0, 1):
                s = (l, r)[i]
                if axis in _spec_axes(s):
                    alts.append(ChargeOption(
                        "all_gather", _nbytes(eqn.invars[i].aval),
                        _spec_axes(s)))
            self.charge("psum", axis, _nbytes(out_aval), out_axes, mult,
                        eqn, alternatives=alts)
        return [out]

    def _h_cumsum(self, env, eqn, specs, mult):
        return [None]

    def _h_cumlogsumexp(self, env, eqn, specs, mult):
        return [None]

    def _h_cummax(self, env, eqn, specs, mult):
        return [None]

    def _h_iota(self, env, eqn, specs, mult):
        return [(None,) * len(eqn.outvars[0].aval.shape)]

    # -- control flow --------------------------------------------------------
    def _h_pjit(self, env, eqn, specs, mult):
        sub = eqn.params.get("jaxpr")
        return self._call(sub, eqn, specs, mult)

    def _h_remat(self, env, eqn, specs, mult):
        return self._call(eqn.params.get("jaxpr"), eqn, specs, mult)

    def _h_checkpoint(self, env, eqn, specs, mult):
        return self._call(eqn.params.get("jaxpr"), eqn, specs, mult)

    def _h_custom_jvp_call(self, env, eqn, specs, mult):
        return self._call(eqn.params.get("call_jaxpr"), eqn, specs, mult)

    def _h_custom_vjp_call(self, env, eqn, specs, mult):
        return self._call(eqn.params.get("call_jaxpr"), eqn, specs, mult)

    def _h_custom_vjp_call_jaxpr(self, env, eqn, specs, mult):
        return self._call(eqn.params.get("fun_jaxpr"), eqn, specs, mult)

    def _call(self, sub, eqn, specs, mult):
        if sub is None:
            return self._generic(eqn, specs, mult)
        inner = sub.jaxpr if hasattr(sub, "jaxpr") else sub
        if len(inner.invars) != len(specs):
            return self._generic(eqn, specs, mult)
        return self.run(sub, specs, mult)

    def _h_scan(self, env, eqn, specs, mult):
        sub = eqn.params.get("jaxpr")
        if sub is None:
            return [None] * len(eqn.outvars)
        n_consts = int(eqn.params.get("num_consts", 0))
        n_carry = int(eqn.params.get("num_carry", 0))
        length = max(1, int(eqn.params.get("length", 1) or 1))
        body_in: typing.List[Spec] = []
        for i, s in enumerate(specs):
            if i < n_consts + n_carry or s is None:
                body_in.append(s)
            else:
                body_in.append(s[1:])  # xs lose the scan dim
        inner = sub.jaxpr if hasattr(sub, "jaxpr") else sub
        if len(inner.invars) != len(body_in):
            return [None] * len(eqn.outvars)
        body_out = self.run(sub, body_in, mult * length)
        outs: typing.List[Spec] = []
        for i, s in enumerate(body_out):
            if i < n_carry or s is None:
                outs.append(s)
            else:
                outs.append((None,) + s)  # ys gain the scan dim
        return outs

    def _h_while(self, env, eqn, specs, mult):
        body = eqn.params.get("body_jaxpr")
        cn = int(eqn.params.get("cond_nconsts", 0))
        bn = int(eqn.params.get("body_nconsts", 0))
        if body is None:
            return [None] * len(eqn.outvars)
        body_in = specs[cn:]
        inner = body.jaxpr if hasattr(body, "jaxpr") else body
        if len(inner.invars) != len(body_in):
            return [None] * len(eqn.outvars)
        # trip count is dynamic: body charged once (documented undercount;
        # none of the audited steps loop collectives inside while bodies)
        outs = self.run(body, body_in, mult)
        del bn  # body outvars ARE the carry, matching eqn.outvars 1:1
        return outs if len(outs) == len(eqn.outvars) else \
            [None] * len(eqn.outvars)

    def _h_cond(self, env, eqn, specs, mult):
        branches = eqn.params.get("branches") or ()
        op_specs = specs[1:]
        best: typing.Optional[typing.List[Spec]] = None
        best_records: typing.List[ImplicitRecord] = []
        best_conflicts: typing.List[Conflict] = []
        best_cost = -1
        for br in branches:
            sub = _Propagator(self.mask)
            inner = br.jaxpr if hasattr(br, "jaxpr") else br
            if len(inner.invars) != len(op_specs):
                return [None] * len(eqn.outvars)
            outs = sub.run(br, op_specs, mult)
            # branch cost proxy: the lowered (first) option of each charge
            cost = sum(r.options[0].full_bytes * r.count
                       for r in sub.records if r.options)
            if cost > best_cost:
                best_cost = cost
                best, best_records = outs, sub.records
                best_conflicts = sub.conflicts
        if best is None:
            return [None] * len(eqn.outvars)
        # charge the costlier branch only (the other executes instead of,
        # not in addition to, it)
        self.records.extend(best_records)
        self.conflicts.extend(best_conflicts)
        return best

    def _generic(self, eqn, specs, mult):
        # recurse into a single sub-jaxpr call if arities line up; else the
        # outputs are unknown (conservative: never invents sharding)
        from .memory import _sub_jaxprs
        subs = list(_sub_jaxprs(eqn))
        if len(subs) == 1:
            inner = subs[0].jaxpr if hasattr(subs[0], "jaxpr") else subs[0]
            if len(inner.invars) == len(specs):
                outs = self.run(subs[0], specs, mult)
                if len(outs) == len(eqn.outvars):
                    return outs
        return [None] * len(eqn.outvars)


# -- entry points ------------------------------------------------------------

def _mask_of(imesh) -> typing.FrozenSet[str]:
    return frozenset(a for a, n in dict(imesh.shape).items() if int(n) > 1)


def propagate(st: StepTrace, imesh) -> SpmdResult:
    """Propagate shardings through one traced step under ``imesh``'s
    active axes.  Memoized per (trace, >1-axis mask): the mesh searcher
    prices many same-structure candidates from one walk."""
    mask = _mask_of(imesh)
    if not mask:
        # single-device mesh: no axis can shard anything, so no record or
        # conflict is reachable — skip the whole walk (the 1-chip configs
        # would otherwise pay a full jaxpr traversal per step inside the
        # CI's graftcheck wall budget for a guaranteed-empty census)
        seeded = st.in_axes is not None
        return SpmdResult(st.name, mask, [], [], seeded=seeded,
                          error="" if seeded
                          else "trace carries no per-input axis metadata")
    cache = getattr(st, "_spmd_cache", None)
    if cache is None:
        cache = {}
        st._spmd_cache = cache
    if mask in cache:
        return cache[mask]
    result = _propagate_uncached(st, mask)
    cache[mask] = result
    return result


def _propagate_uncached(st: StepTrace, mask: typing.FrozenSet[str]
                        ) -> SpmdResult:
    from ..parallel.sharding import spec_for
    inner = st.jaxpr.jaxpr if hasattr(st.jaxpr, "jaxpr") else st.jaxpr
    if st.in_axes is None:
        return SpmdResult(st.name, mask, [], [], seeded=False,
                          error="trace carries no per-input axis metadata")
    mesh = _MaskMesh(mask)
    seeds: typing.List[Spec] = []
    for v, names in zip(inner.invars, st.in_axes):
        rank = len(getattr(getattr(v, "aval", None), "shape", ()) or ())
        if names is None:
            seeds.append(None)
            continue
        if len(names) == 0:
            seeds.append((None,) * rank)  # explicitly replicated
            continue
        if len(names) != rank:
            # rank drift (e.g. stacked-pipeline axis metadata against an
            # unstacked decode leaf): seeding a truncated spec would plant
            # a WRONG sharding — unknown is the honest seed
            seeds.append(None)
            continue
        spec = spec_for(tuple(names), mesh)
        parts: typing.List[typing.Optional[str]] = []
        for part in spec:
            axes = [a for a in (part if isinstance(part, tuple) else (part,))
                    if a is not None]
            parts.append(axes[0] if axes else None)
        parts += [None] * (rank - len(parts))
        seeds.append(_dedup(parts[:rank]))
    prop = _Propagator(mask)
    try:
        prop.run(st.jaxpr, seeds)
    except Exception as e:  # pragma: no cover - surfaced as a rule error
        return SpmdResult(st.name, mask, prop.records, prop.conflicts,
                          seeded=True,
                          error=f"{type(e).__name__}: {e}")
    return SpmdResult(st.name, mask, prop.records, prop.conflicts,
                      seeded=True)


def census(result: SpmdResult, imesh, strategy: str = "lowered"
           ) -> typing.Dict[str, typing.Dict[str, typing.Dict[str, int]]]:
    """Concrete implicit census under ``imesh``: ``{family: {axis:
    {count, payload_bytes, bytes}}}``.  ``payload_bytes`` is the
    per-device buffer entering the collective (the figure HLO validation
    compares); ``bytes`` is ring-moved bytes (chunk-factored — what the
    alpha-beta pricing consumes).

    ``strategy``: ``"lowered"`` takes each record's FIRST option — the
    partial-sum all-reduce today's partitioner actually emits, which is
    what the HLO cross-validation pins and the golden ratchets;
    ``"best"`` takes the per-site cheapest option (e.g. all-gather a
    weight that is tiny next to a batch-sized partial-sum output) — the
    tuned-lowering bound the pricing uses (see :func:`implicit_comm`)."""
    from .cost_model import _CHUNK_FACTORS
    if strategy not in ("lowered", "best"):
        raise ValueError(f"unknown census strategy {strategy!r}")
    shape = {a: int(n) for a, n in dict(imesh.shape).items()}
    out: typing.Dict[str, typing.Dict[str, typing.Dict[str, int]]] = {}
    for r in result.records:
        n = shape.get(r.axis, 1)
        if n <= 1:
            continue
        best: typing.Optional[typing.Tuple[float, float, str]] = None
        for o in (r.options if strategy == "best" else r.options[:1]):
            div = 1
            for a in o.shard_axes:
                div *= max(1, shape.get(a, 1))
            payload = o.full_bytes / div
            moved = payload * _CHUNK_FACTORS.get(o.family,
                                                 lambda n: 1.0)(n)
            if best is None or moved < best[0]:
                best = (moved, payload, o.family)
        if best is None:
            continue
        moved, payload, family = best
        slot = out.setdefault(family, {}).setdefault(
            r.axis, {"count": 0, "payload_bytes": 0, "bytes": 0})
        slot["count"] += r.count
        slot["payload_bytes"] += int(payload * r.count)
        slot["bytes"] += int(moved * r.count)
    return out


#: the XLA collective-combiner model: implicit same-axis collectives fuse
#: into one launch per this much payload (the census keeps true per-op
#: counts — those are what the uncombined CPU-lowered HLO shows — but the
#: alpha/launch pricing must not charge a latency per tiny gradient psum
#: that the combiner pass provably batches)
COMBINE_BYTES = 32 * 1024 * 1024


def implicit_comm(result: SpmdResult, imesh):
    """The implicit census as a :class:`cost_model.CommModel` (moved bytes
    per axis) — the term ``StepResources.total_comm`` adds on top of the
    walked manual collectives.  Priced at the ``"best"`` strategy (the
    partitioner's cheaper choice per site) with launch counts
    combiner-fused (:data:`COMBINE_BYTES`) — the tuned-lowering bound the
    mesh-search objective should rank on — while the ratcheted census and
    the HLO validation pin the ``"lowered"`` form (docs/static_analysis.md
    "SPMD propagation")."""
    from .cost_model import CommModel
    c = census(result, imesh, strategy="best")
    model = CommModel({}, {})
    for fam in c.values():
        for axis, slot in fam.items():
            model.bytes_per_axis[axis] = (
                model.bytes_per_axis.get(axis, 0) + int(slot["bytes"]))
            launches = max(1, -(-int(slot["payload_bytes"]) // COMBINE_BYTES))
            model.count_per_axis[axis] = (
                model.count_per_axis.get(axis, 0)
                + min(int(slot["count"]), launches))
    return model


# -- the ratcheted implicit-collective graph rule ----------------------------

def spmd_golden_path(config_name: str) -> str:
    return os.path.join(GOLDENS_DIR, "spmd", config_name + ".json")


def _loc(traces: ConfigTraces, step: str) -> str:
    return f"configs/{traces.config_name}.json[{step}]"


def _step_golden(result: SpmdResult, imesh) -> dict:
    return {"implicit": census(result, imesh),
            "conflicts": len(result.conflicts),
            "seeded": bool(result.seeded)}


def check_implicit_collectives(traces: ConfigTraces,
                               update_goldens: bool = False
                               ) -> typing.List[Finding]:
    """The graph rule: the propagated implicit-collective census vs the
    ratcheted golden (``analysis/goldens/spmd/<config>.json``).  Counts
    must match exactly (a new implicit reshard is a layout change, exactly
    like a census diff); per-axis moved bytes ratchet at
    :data:`RATCHET_RATIO`; sharding conflicts lint as warnings and their
    count may never grow past the recorded one."""
    from .graph_rules import intended_mesh
    findings: typing.List[Finding] = []
    imesh = intended_mesh(traces.cfg)
    actual: typing.Dict[str, dict] = {}
    results: typing.Dict[str, SpmdResult] = {}
    for step, st in sorted(traces.steps.items()):
        try:
            r = propagate(st, imesh)
        except Exception as e:  # a propagation crash must name itself
            findings.append(Finding(
                "implicit-collective", "error", _loc(traces, step),
                f"sharding propagation failed: {type(e).__name__}: {e}"))
            continue
        if r.error:
            findings.append(Finding(
                "implicit-collective", "error", _loc(traces, step),
                f"sharding propagation failed: {r.error}"))
            continue
        if not r.seeded:
            findings.append(Finding(
                "implicit-collective", "warning", _loc(traces, step),
                "step trace carries no per-input sharding seeds "
                "(in_axes) — implicit collectives unaudited for this step"))
            continue
        results[step] = r
        actual[step] = _step_golden(r, imesh)
        for c in r.conflicts[:5]:
            findings.append(Finding(
                "implicit-collective", "warning", _loc(traces, step),
                f"conflicting operand shardings at {c.location} "
                f"({c.prim}: {c.detail}) — GSPMD resolves this by "
                f"resharding, often to full replication"))
        if len(r.conflicts) > 5:
            findings.append(Finding(
                "implicit-collective", "warning", _loc(traces, step),
                f"... and {len(r.conflicts) - 5} more sharding conflicts"))
    path = spmd_golden_path(traces.config_name)
    if update_goldens:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        import jax
        merged = dict(actual)
        if os.path.exists(path):
            with open(path) as f:
                for step, budget in json.load(f).get("steps", {}).items():
                    merged.setdefault(step, budget)
        with open(path, "w") as f:
            json.dump({"config": traces.config_name,
                       "jax": jax.__version__,
                       "intended_mesh": {k: int(v) for k, v in
                                         dict(imesh.shape).items()},
                       "tolerance": {"ratchet": RATCHET_RATIO},
                       "steps": merged}, f, indent=2, sort_keys=True)
            f.write("\n")
        findings.append(Finding(
            "implicit-collective", "info", path,
            f"spmd golden updated ({', '.join(actual) or 'no steps'})"))
        return findings
    if not os.path.exists(path):
        findings.append(Finding(
            "implicit-collective", "error", _loc(traces, "*"),
            f"no spmd golden at {os.path.relpath(path)}; run "
            f"`python tools/graftcheck.py --config configs/"
            f"{traces.config_name}.json --update-goldens`"))
        return findings
    with open(path) as f:
        golden = json.load(f)
    ratchet = float(golden.get("tolerance", {}).get("ratchet", RATCHET_RATIO))
    gsteps = golden.get("steps", {})
    from .cost_model import format_bytes as _fmt
    for step in sorted(set(actual) | set(gsteps)):
        if step not in actual:
            findings.append(Finding(
                "implicit-collective", "warning", _loc(traces, step),
                "step present in spmd golden but not audited this run "
                f"({traces.errors.get(step, 'step skipped')})"))
            continue
        if step not in gsteps:
            findings.append(Finding(
                "implicit-collective", "warning", _loc(traces, step),
                "step audited but not pinned by the spmd golden; record it "
                "with --update-goldens to gate it"))
            continue
        got, want = actual[step], gsteps[step]
        gi, wi = got.get("implicit", {}), want.get("implicit", {})
        for fam in sorted(set(gi) | set(wi)):
            ga, wa = gi.get(fam, {}), wi.get(fam, {})
            for ax in sorted(set(ga) | set(wa)):
                g = ga.get(ax, {"count": 0, "bytes": 0})
                w = wa.get(ax, {"count": 0, "bytes": 0})
                if g["count"] != w.get("count", 0):
                    findings.append(Finding(
                        "implicit-collective", "error", _loc(traces, step),
                        f"implicit {fam} count on mesh axis {ax!r} "
                        f"{g['count']} != golden {w.get('count', 0)} — "
                        f"GSPMD will insert a different collective layout "
                        f"than the one this config was tuned under "
                        f"(an unplanned reshard usually means a "
                        f"sharding-spec drift); if intended, re-record "
                        f"with --update-goldens"))
                elif g["bytes"] > max(w.get("bytes", 0), 1024) * ratchet:
                    findings.append(Finding(
                        "implicit-collective", "error", _loc(traces, step),
                        f"implicit {fam} payload on mesh axis {ax!r} grew "
                        f"{_fmt(w.get('bytes', 0))} -> {_fmt(g['bytes'])} "
                        f"past the x{ratchet} ratchet; if intended, "
                        f"re-record with --update-goldens"))
                elif (w.get("bytes", 0) > 1024
                        and g["bytes"] * ratchet < w["bytes"]):
                    findings.append(Finding(
                        "implicit-collective", "info", _loc(traces, step),
                        f"implicit {fam} payload on {ax!r} improved "
                        f"{_fmt(w['bytes'])} -> {_fmt(g['bytes'])}; "
                        f"re-record with --update-goldens to ratchet"))
        if got.get("conflicts", 0) > want.get("conflicts", 0):
            findings.append(Finding(
                "implicit-collective", "error", _loc(traces, step),
                f"sharding conflicts grew {want.get('conflicts', 0)} -> "
                f"{got['conflicts']} — a new eqn mixes incompatible "
                f"operand shardings (see the conflict warnings above); if "
                f"intended, re-record with --update-goldens"))
        elif got.get("conflicts", 0) < want.get("conflicts", 0):
            findings.append(Finding(
                "implicit-collective", "info", _loc(traces, step),
                f"sharding conflicts improved {want.get('conflicts', 0)} "
                f"-> {got['conflicts']}; re-record with --update-goldens"))
    return findings


# -- HLO cross-validation (the honesty check) --------------------------------

def hlo_compilable(cfg) -> typing.Tuple[bool, str]:
    """Whether the config's train step can be lowered+compiled with its
    real shardings on THIS host (CPU devices): pure-GSPMD structure only
    (the sequence/pipeline axes lower through shard_map, which the compat
    shims stage but cannot faithfully compile), and enough local devices
    to build the intended mesh."""
    import jax
    if int(cfg.sequence_parallel) > 1 or int(cfg.pipeline_parallel) > 1:
        return False, "sequence/pipeline structure lowers through " \
                      "shard_map — not GSPMD-compilable here"
    n = max(1, int(cfg.tpu_size))
    if n > len(jax.devices()):
        return False, (f"intended mesh needs {n} devices; "
                       f"{len(jax.devices())} available")
    return True, ""


def compile_train_hlo(cfg) -> str:
    """Lower + compile the REAL train step under the intended shardings on
    local (CPU) devices and return the optimized, partitioned HLO text —
    the collectives in it are the ones GSPMD actually inserted."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec
    from ..nd import NT
    from ..optim import Optimizer
    from ..parallel import make_mesh
    from ..parallel.sharding import spec_for
    from ..train.state import Trainer, TrainState
    from .trace import _micro_sds, abstract_batch, abstract_params, \
        trace_compat
    n = max(1, int(cfg.tpu_size))
    mesh = make_mesh(cfg, devices=jax.devices()[:n], quiet=True)
    batch = abstract_batch(cfg)
    trainer = Trainer(cfg, mesh)
    micro = _micro_sds(batch, trainer.n_micro)
    params, axes = abstract_params(cfg, micro)
    trainer.axes = axes
    trainer.optimizer = Optimizer(cfg, axes)
    opt_state = jax.eval_shape(trainer.optimizer.init, params)
    slot_axes = trainer.optimizer.slot_axis_names()

    def shard(sds, names):
        return jax.ShapeDtypeStruct(
            sds.shape, sds.dtype,
            sharding=NamedSharding(mesh, spec_for(tuple(names), mesh)))

    state = TrainState(
        {k: shard(v, axes.get(k, ())) for k, v in params.items()},
        {name: {k: shard(v, slot_axes.get(name, {}).get(k, ()))
                for k, v in slots.items()}
         for name, slots in dict(opt_state).items()},
        jax.ShapeDtypeStruct((), jnp.int32,
                             sharding=NamedSharding(mesh, PartitionSpec())))
    sbatch = {k: NT(shard(t.x, t.names), t.names) for k, t in batch.items()}
    rng = jax.eval_shape(lambda: jax.random.key(0))
    rng = jax.ShapeDtypeStruct(
        rng.shape, rng.dtype,
        sharding=NamedSharding(mesh, PartitionSpec()))
    step = trainer._make_step()
    with trace_compat(), mesh:
        compiled = step.trace(state, sbatch, rng,
                              *trainer.step_extra_args()).lower().compile()
    return compiled.as_text()


_HLO_OP_RE = re.compile(
    r"=\s+(?P<shape>\(?[a-z0-9_]+\[[^=]*?)\s+"
    r"(?P<op>" + "|".join(HLO_COLLECTIVE_OPS) + r")(?:-start)?[.\d]*\(")
_HLO_SHAPE_RE = re.compile(r"([a-z][a-z0-9_]*)\[([0-9,]*)\]")

_HLO_DTYPE_BYTES = {
    "f64": 8, "f32": 4, "f16": 2, "bf16": 2, "f8e4m3fn": 1, "f8e5m2": 1,
    "s64": 8, "u64": 8, "s32": 4, "u32": 4, "s16": 2, "u16": 2,
    "s8": 1, "u8": 1, "pred": 1, "c64": 8, "c128": 16,
}


def hlo_collectives(text: str) -> typing.Dict[str, typing.Dict[str, int]]:
    """Parse ``{op: {count, bytes}}`` out of partitioned HLO text; bytes
    sum the (per-device) result shapes, tuple results included, so XLA's
    all-reduce combiner merging N ops into one keeps bytes invariant."""
    out: typing.Dict[str, typing.Dict[str, int]] = {}
    for m in _HLO_OP_RE.finditer(text):
        op = m.group("op")
        nbytes = 0
        for dt, dims in _HLO_SHAPE_RE.findall(m.group("shape")):
            n = 1
            for d in dims.split(","):
                if d.strip():
                    n *= int(d)
            nbytes += n * _HLO_DTYPE_BYTES.get(dt, 4)
        slot = out.setdefault(op, {"count": 0, "bytes": 0})
        slot["count"] += 1
        slot["bytes"] += nbytes
    return out


def compare_hlo(predicted: typing.Dict[str, typing.Dict[str, typing.Dict[
        str, int]]], hlo: typing.Dict[str, typing.Dict[str, int]]) -> dict:
    """Tolerance verdict between a predicted implicit census and the
    lowered-HLO collective sheet.  Totals are the comparison basis — XLA
    combines/splits ops freely but conserves payload bytes; counts get a
    loose bar, bytes the :data:`HLO_TOLERANCE` ratio with
    :data:`HLO_BYTES_SLACK` absolute slack."""
    pred_count = sum(slot["count"] for fam in predicted.values()
                     for slot in fam.values())
    pred_bytes = sum(slot["payload_bytes"] for fam in predicted.values()
                     for slot in fam.values())
    hlo_count = sum(v["count"] for v in hlo.values())
    hlo_bytes = sum(v["bytes"] for v in hlo.values())
    reasons: typing.List[str] = []
    if (pred_count > 0) != (hlo_count > 0):
        reasons.append(
            f"presence mismatch: predicted {pred_count} implicit "
            f"collective(s), lowered HLO has {hlo_count}")
    if abs(pred_count - hlo_count) > max(HLO_COUNT_SLACK,
                                         0.5 * max(pred_count, hlo_count)):
        reasons.append(f"count mismatch: predicted {pred_count} vs "
                       f"{hlo_count} in HLO")
    lo = min(pred_bytes, hlo_bytes) + HLO_BYTES_SLACK
    hi = max(pred_bytes, hlo_bytes)
    if hi > lo * HLO_TOLERANCE:
        reasons.append(
            f"payload mismatch: predicted {pred_bytes} B vs {hlo_bytes} B "
            f"in HLO (beyond x{HLO_TOLERANCE} + {HLO_BYTES_SLACK} B slack)")
    return {"ok": not reasons,
            "predicted": {"count": pred_count, "payload_bytes": pred_bytes},
            "hlo": {"count": hlo_count, "bytes": hlo_bytes,
                    "ops": hlo},
            "tolerance": {"bytes_ratio": HLO_TOLERANCE,
                          "bytes_slack": HLO_BYTES_SLACK,
                          "count_slack": HLO_COUNT_SLACK},
            "reasons": reasons}


def validate_hlo(traces: ConfigTraces) -> dict:
    """The honesty check: predicted implicit census (train step, intended
    mesh) vs the collectives in the actually-compiled partitioned HLO.
    Returns ``{"skipped": reason}`` when the config cannot compile here
    (shard_map structure / not enough local devices / no train trace)."""
    from .graph_rules import intended_mesh
    cfg = traces.cfg
    ok, reason = hlo_compilable(cfg)
    if not ok:
        return {"config": traces.config_name, "skipped": reason}
    st = traces.steps.get("train")
    if st is None:
        return {"config": traces.config_name,
                "skipped": traces.errors.get("train", "train not traced")}
    imesh = intended_mesh(cfg)
    result = propagate(st, imesh)
    if not result.seeded or result.error:
        return {"config": traces.config_name,
                "skipped": result.error or "no sharding seeds"}
    predicted = census(result, imesh)
    try:
        text = compile_train_hlo(cfg)
    except Exception as e:
        return {"config": traces.config_name,
                "skipped": f"compile failed: {type(e).__name__}: {e}"}
    verdict = compare_hlo(predicted, hlo_collectives(text))
    verdict["config"] = traces.config_name
    verdict["predicted_census"] = predicted
    return verdict
