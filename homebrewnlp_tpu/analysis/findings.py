"""Finding record + report rendering for graftcheck.

A finding pins one violated invariant to a location (source file:line for AST
rules, config + step for graph rules).  Severities:

- ``error``   — the invariant is broken; graftcheck exits non-zero.
- ``warning`` — suspicious but not certainly wrong (e.g. a large tensor left
  fully replicated); reported, exit 0 unless ``--strict``.
- ``info``    — bookkeeping (e.g. a ratchet count that IMPROVED and should be
  re-recorded); never affects the exit code.
"""
from __future__ import annotations

import dataclasses
import json
import typing

Severity = str  # "error" | "warning" | "info"
_SEVERITY_ORDER = {"info": 0, "warning": 1, "error": 2}


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    severity: Severity
    location: str  # "path/to/file.py:123" or "configs/x.json[train]"
    message: str

    def render(self) -> str:
        return f"{self.severity.upper():7s} [{self.rule}] {self.location}: {self.message}"


def worst_severity(findings: typing.Iterable[Finding]) -> typing.Optional[Severity]:
    worst = None
    for f in findings:
        if worst is None or _SEVERITY_ORDER[f.severity] > _SEVERITY_ORDER[worst]:
            worst = f.severity
    return worst


def render_report(findings: typing.Sequence[Finding], as_json: bool = False) -> str:
    if as_json:
        return json.dumps([dataclasses.asdict(f) for f in findings], indent=2)
    if not findings:
        return "graftcheck: clean — no findings"
    lines = []
    by_rule: typing.Dict[str, typing.List[Finding]] = {}
    for f in findings:
        by_rule.setdefault(f.rule, []).append(f)
    for rule in sorted(by_rule):
        group = by_rule[rule]
        lines.append(f"-- {rule} ({len(group)}) " + "-" * max(0, 58 - len(rule)))
        lines.extend(f.render() for f in group)
    n_err = sum(1 for f in findings if f.severity == "error")
    n_warn = sum(1 for f in findings if f.severity == "warning")
    n_info = len(findings) - n_err - n_warn
    lines.append(f"graftcheck: {n_err} error(s), {n_warn} warning(s), {n_info} info")
    return "\n".join(lines)
