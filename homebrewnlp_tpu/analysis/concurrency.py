"""Static concurrency model of the threaded host layer (sync-* rules).

The serving/observability/supervision layers are plain-Python threads around
the jax core; graftcheck's graph rules cannot see them.  This module builds
two static models from the AST of the threaded scopes (``SYNC_SCOPES``) and
pins both against goldens, in the graftspmd house style (a static prediction
validated against runtime reality — see ``tools/graftsync.py --validate``):

**Shared-state model (rule ``sync-shared-state``).**  Every instance
attribute access is attributed to the *thread identities* that can execute
it:

- ``init`` — reached (only) from ``__init__``/``__del__``/class body; never
  concurrent by construction and ignored.
- ``caller`` — the single bucket for externally-called public methods (the
  analysis does not distinguish which caller thread).
- ``thread:<Class>.<m>`` — a dedicated thread entry point: ``Thread(target=
  self.m)`` / ``threading.Timer(..., self.m)`` targets, nested functions
  passed as thread targets, and ``run()`` on Thread subclasses.
- ``callback:<Class>.<m>`` — a bound method or lambda that escapes as a call
  argument (gauge functions, probes, sinks): it may run on whichever thread
  owns the registry it was handed to.

Identities propagate through *resolvable* calls only: ``self.m()``, calls on
attributes with inferred types (``self.x = ClassName(...)`` or annotated
``__init__`` params), and same-module function calls.  An attribute is
*concurrent* when its accesses span >= 2 identities of which at least one is
a ``thread:``/``callback:`` identity; if any such attribute has a non-init
write, every access must sit inside a ``with <declared lock>:`` scope of a
lock declared on the owning class (or its module) — except reads whose
identities are a subset of the sole writing identity (a single writer may
read its own latest value).  Violation counts are ratcheted in
``goldens/sync/shared_state.json`` (committed EMPTY: every true finding was
fixed rather than allowlisted, so any new unguarded shared write is an
error).  Suppress a deliberate site with ``# graftcheck:
disable=sync-shared-state -- reason``.

Documented blind spots (by design — the rule is a ratchet, not a verifier):
module-global mutable state, writes through method calls (``list.append``)
or subscripts (``d[k] = v`` counts as a *read* of ``d``), and writes from
outside the owning class.

**Lock-order graph (rule ``sync-lock-order``).**  Locks are *declared*
through ``homebrewnlp_tpu.sync.make_lock/make_rlock/make_condition`` with
their graph name (``<module>.<Class>.<attr>``; the analyzer derives the same
id from the AST and warns on mismatch, and warns on raw ``threading.*``
declarations which the runtime recorder cannot see).  Edges ``A -> B`` mean
"B was acquired while A was held": lexically nested ``with`` scopes, plus
calls into lock-acquiring code while holding (closed transitively over the
resolvable call graph; unresolvable ``x.m()`` calls fall back to *every*
scanned method named ``m`` so the static graph over- rather than
under-approximates what the runtime recorder can observe).  Any cycle is a
potential deadlock and always an error.  The edge set is pinned in
``goldens/sync/lock_order.json``: a new edge is an error until reviewed and
re-recorded (``--update-goldens``), a vanished edge is an info.

**Runtime validation** (``validate_recorded``): every ``held -> acquired``
edge logged by the ``HBNLP_SYNC_RECORD=1`` recording shim while the real
suites run must already be in the static graph — a recorded edge the model
missed fails, exactly like graftspmd's HLO collective pin.
"""
from __future__ import annotations

import ast
import json
import os
import typing

from .ast_rules import _check_ratchet, _iter_py_files, _suppressed
from .findings import Finding

#: threaded scopes the sync rules audit (package-relative).  tools/
#: graftload.py is deliberately out: it is a load-generation *client* with
#: one function-local lock and no shared engine state.
SYNC_SCOPES = ("homebrewnlp_tpu/serve", "homebrewnlp_tpu/obs",
               "homebrewnlp_tpu/data", "homebrewnlp_tpu/infer",
               "homebrewnlp_tpu/reliability", "homebrewnlp_tpu/native",
               "tools/supervise.py")

#: analysis itself and the recorder are infrastructure, not subjects
SYNC_EXEMPT = ("homebrewnlp_tpu/sync.py",)

_FACTORY_KINDS = {"make_lock": "lock", "make_rlock": "rlock",
                  "make_condition": "condition"}
_RAW_KINDS = {"Lock": "lock", "RLock": "rlock", "Condition": "condition"}
_THREAD_CTORS = frozenset({"Thread"})
_TIMER_CTORS = frozenset({"Timer"})
_INIT_METHODS = frozenset({"__init__", "__del__", "__post_init__",
                           "__set_name__"})
#: dunders that are ordinary external entry points
_PUBLIC_DUNDERS = frozenset({"__call__", "__iter__", "__next__",
                             "__enter__", "__exit__", "__contains__",
                             "__len__", "__repr__", "__str__"})


class _Lock:
    __slots__ = ("lid", "kind", "factory", "literal", "rel", "lineno")

    def __init__(self, lid, kind, factory, literal, rel, lineno):
        self.lid, self.kind, self.factory = lid, kind, factory
        self.literal, self.rel, self.lineno = literal, rel, lineno


class _Access:
    __slots__ = ("owner", "attr", "write", "guards", "rel", "lineno", "ctx")

    def __init__(self, owner, attr, write, guards, rel, lineno, ctx):
        self.owner, self.attr, self.write = owner, attr, write
        self.guards, self.rel, self.lineno, self.ctx = guards, rel, lineno, ctx


class _Ctx:
    """One execution context: a method, module function, or a synthetic
    context for an escaping lambda / thread-target nested function."""
    __slots__ = ("key", "seeds", "ids", "calls", "acquires", "call_events",
                 "direct_locks")

    def __init__(self, key):
        self.key = key
        self.seeds: typing.Set[str] = set()
        self.ids: typing.Set[str] = set()
        self.calls: typing.List[tuple] = []          # resolvable + name specs
        self.acquires: typing.List[tuple] = []       # (held tuple, lid, rel, line)
        self.call_events: typing.List[tuple] = []    # (held tuple, spec, rel, line)
        self.direct_locks: typing.Set[str] = set()


class SyncModel:
    def __init__(self):
        self.locks: typing.Dict[str, _Lock] = {}
        self.class_locks: typing.Dict[tuple, typing.Dict[str, str]] = {}
        self.module_locks: typing.Dict[str, typing.Dict[str, str]] = {}
        self.class_index: typing.Dict[str, tuple] = {}   # name -> (mod, rel)
        self.class_methods: typing.Dict[tuple, typing.Set[str]] = {}
        self.attr_types: typing.Dict[tuple, typing.Dict[str, str]] = {}
        self.ctxs: typing.Dict[tuple, _Ctx] = {}
        self.accesses: typing.List[_Access] = []
        self.escapes: typing.Set[str] = set()        # method names passed as args
        self.thread_roots: typing.Set[tuple] = set() # (mod, cls, meth)
        self.warnings: typing.List[Finding] = []
        self.edges: typing.Dict[tuple, typing.Set[str]] = {}  # (a,b) -> locs
        self.lines: typing.Dict[str, typing.List[str]] = {}   # rel -> lines
        self.module_funcs: typing.Dict[str, typing.Set[str]] = {}

    def ctx(self, key: tuple) -> _Ctx:
        if key not in self.ctxs:
            self.ctxs[key] = _Ctx(key)
        return self.ctxs[key]


def _modstem(rel: str) -> str:
    norm = rel.replace(os.sep, "/")
    if norm.startswith("homebrewnlp_tpu/"):
        norm = norm[len("homebrewnlp_tpu/"):]
    if norm.endswith("/__init__.py"):
        norm = norm[:-len("/__init__.py")]
    elif norm.endswith(".py"):
        norm = norm[:-3]
    return norm.replace("/", ".")


def _terminal(func: ast.expr) -> typing.Optional[str]:
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def _ann_name(node: typing.Optional[ast.expr]) -> typing.Optional[str]:
    """Class name out of an annotation: Name, string constant, or the inner
    of a single-arg subscript (Optional[...])."""
    if node is None:
        return None
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value.split(".")[-1].strip()
    if isinstance(node, ast.Subscript):
        return _ann_name(node.slice)
    return None


def _self_attr(node: ast.expr) -> typing.Optional[str]:
    if (isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _thread_target(call: ast.Call) -> typing.Optional[ast.expr]:
    """The callable a Thread/Timer construction will run on a new thread."""
    term = _terminal(call.func)
    if term in _THREAD_CTORS:
        for kw in call.keywords:
            if kw.arg == "target":
                return kw.value
        if len(call.args) >= 2:
            return call.args[1]
    elif term in _TIMER_CTORS:
        for kw in call.keywords:
            if kw.arg == "function":
                return kw.value
        if len(call.args) >= 2:
            return call.args[1]
    return None


class _FileScanner:
    """Phase-B visitor for one module: builds contexts, accesses, lock
    acquisition / call events.  ``model.class_index`` is complete (phase A
    ran over every file) so constructor-based attr typing resolves
    cross-module."""

    def __init__(self, model: SyncModel, rel: str, tree: ast.Module):
        self.m = model
        self.rel = rel
        self.mod = _modstem(rel)
        self.tree = tree

    # -- lock resolution -------------------------------------------------
    def _lock_of(self, expr: ast.expr, cls: typing.Optional[str]
                 ) -> typing.Optional[str]:
        if isinstance(expr, ast.Name):
            return self.m.module_locks.get(self.mod, {}).get(expr.id)
        attr = _self_attr(expr)
        if attr is not None and cls is not None:
            return self.m.class_locks.get((self.mod, cls), {}).get(attr)
        # self.X._lock with type(X) known
        if isinstance(expr, ast.Attribute):
            base = _self_attr(expr.value)
            if base is not None and cls is not None:
                t = self.m.attr_types.get((self.mod, cls), {}).get(base)
                if t and t in self.m.class_index:
                    tmod, _ = self.m.class_index[t]
                    return self.m.class_locks.get((tmod, t), {}).get(expr.attr)
        return None

    # -- main entry ------------------------------------------------------
    def scan(self) -> None:
        for node in self.tree.body:
            if isinstance(node, ast.ClassDef):
                self._scan_class(node)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._scan_func(node, cls=None)

    def _scan_class(self, cnode: ast.ClassDef) -> None:
        cls = cnode.name
        is_thread_subclass = any(_terminal(b) == "Thread"
                                 for b in cnode.bases)
        for node in cnode.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if is_thread_subclass and node.name == "run":
                    self.m.thread_roots.add((self.mod, cls, "run"))
                self._scan_func(node, cls=cls)

    def _scan_func(self, fnode, cls: typing.Optional[str]) -> None:
        key = (self.mod, cls, fnode.name)
        ctx = self.m.ctx(key)
        if cls is not None and fnode.name in _INIT_METHODS:
            ctx.seeds.add("init")
        elif cls is None or not fnode.name.startswith("_") \
                or fnode.name in _PUBLIC_DUNDERS:
            # thread roots discovered later drop this seed in _finalize
            ctx.seeds.add("caller")
        self._prescan_nested(fnode)
        self._visit_block(fnode.body, ctx, cls, guards=(), meth=fnode.name)

    def _prescan_nested(self, fnode) -> None:
        """Classify nested defs BEFORE visiting bodies (the Thread(...)
        call usually comes lexically after the def it targets)."""
        defs: typing.Dict[str, typing.Any] = {}
        for sub in ast.walk(fnode):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and sub is not fnode:
                defs[sub.name] = sub
        if not defs:
            return
        for call in ast.walk(fnode):
            if not isinstance(call, ast.Call):
                continue
            target = _thread_target(call)
            if isinstance(target, ast.Name) and target.id in defs:
                self._nested_usage[id(defs[target.id])] = "thread"
                continue
            for arg in list(call.args) + [kw.value for kw in call.keywords]:
                if isinstance(arg, ast.Name) and arg.id in defs:
                    self._nested_usage.setdefault(id(defs[arg.id]),
                                                  "callback")

    # -- statement walk --------------------------------------------------
    def _visit_block(self, stmts, ctx: _Ctx, cls, guards: tuple,
                     meth: str) -> None:
        for st in stmts:
            self._visit_stmt(st, ctx, cls, guards, meth)

    def _visit_stmt(self, st, ctx: _Ctx, cls, guards: tuple,
                    meth: str) -> None:
        if isinstance(st, ast.With) or isinstance(st, ast.AsyncWith):
            inner = guards
            for item in st.items:
                lid = self._lock_of(item.context_expr, cls)
                self._visit_expr(item.context_expr, ctx, cls, inner, meth)
                if lid is not None:
                    ctx.acquires.append((inner, lid, self.rel,
                                         item.context_expr.lineno))
                    ctx.direct_locks.add(lid)
                    inner = inner + (lid,)
            self._visit_block(st.body, ctx, cls, inner, meth)
            return
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self._scan_nested(st, ctx, cls, meth)
            return
        if isinstance(st, ast.ClassDef):
            return  # nested classes: out of scope
        # generic expression fields first (targets/values), then sub-blocks
        for field in ("targets", "target", "value", "test", "iter", "exc",
                      "cause", "msg"):
            v = getattr(st, field, None)
            if isinstance(v, list):
                for e in v:
                    self._visit_expr(e, ctx, cls, guards, meth)
            elif isinstance(v, ast.expr):
                self._visit_expr(v, ctx, cls, guards, meth)
        if isinstance(st, ast.AnnAssign) and st.annotation is not None:
            pass  # annotations carry no runtime accesses
        for block in ("body", "orelse", "finalbody"):
            b = getattr(st, block, None)
            if isinstance(b, list):
                self._visit_block(b, ctx, cls, guards, meth)
        for h in getattr(st, "handlers", ()) or ():
            self._visit_block(h.body, ctx, cls, guards, meth)
        if isinstance(st, ast.Return) and st.value is not None:
            pass  # already covered by "value"
        if isinstance(st, (ast.Expr,)):
            pass  # covered by "value"

    def _scan_nested(self, fnode, parent: _Ctx, cls, meth: str,
                     forced: typing.Optional[str] = None) -> None:
        """Nested def: thread target -> own thread context; passed as a
        call argument -> callback context; only called inline -> folded
        into the parent context (callbacks run outside the registration-
        time guards, so nested bodies start with an empty guard stack)."""
        usage = forced or self._nested_usage.get(id(fnode), "inline")
        if usage == "inline":
            self._visit_block(fnode.body, parent, cls, guards=(), meth=meth)
            return
        mod, pcls, _ = parent.key
        key = (mod, pcls, f"{meth}.{fnode.name}")
        sub = self.m.ctx(key)
        qual = f"{pcls or mod}.{meth}.{fnode.name}"
        sub.seeds.add(f"{usage}:{qual}")
        self._visit_block(fnode.body, sub, cls, guards=(), meth=meth)

    # -- expression walk -------------------------------------------------
    def _visit_expr(self, e, ctx: _Ctx, cls, guards: tuple, meth: str,
                    as_arg: bool = False) -> None:
        if e is None or not isinstance(e, ast.expr):
            return
        if isinstance(e, ast.Lambda):
            mod, pcls, _ = ctx.key
            key = (mod, pcls, f"{meth}.<lambda@{e.lineno}>")
            sub = self.m.ctx(key)
            sub.seeds.add(f"callback:{pcls or mod}.{meth}.<lambda>")
            self._visit_expr(e.body, sub, cls, (), meth)
            return
        if isinstance(e, ast.Call):
            self._visit_call(e, ctx, cls, guards, meth)
            return
        if isinstance(e, ast.Attribute):
            self._record_attr(e, ctx, cls, guards, as_arg)
            # descend into the base object expression
            self._visit_expr(e.value, ctx, cls, guards, meth)
            return
        for child in ast.iter_child_nodes(e):
            if isinstance(child, ast.expr):
                self._visit_expr(child, ctx, cls, guards, meth,
                                 as_arg=as_arg and isinstance(
                                     e, (ast.Tuple, ast.List)))
            elif isinstance(child, ast.comprehension):
                self._visit_expr(child.iter, ctx, cls, guards, meth)
                for cond in child.ifs:
                    self._visit_expr(cond, ctx, cls, guards, meth)

    def _record_attr(self, e: ast.Attribute, ctx: _Ctx, cls, guards: tuple,
                     as_arg: bool) -> None:
        attr = _self_attr(e)
        if attr is not None and cls is not None:
            write = isinstance(e.ctx, (ast.Store, ast.Del))
            self.m.accesses.append(_Access(
                (self.mod, cls), attr, write, frozenset(guards),
                self.rel, e.lineno, ctx.key))
            if as_arg and not write \
                    and attr in self.m.class_methods.get((self.mod, cls),
                                                         ()):
                self.m.escapes.add(attr)
            return
        # self.X.Y with type(X) known: a cross-object access on type(X)
        base = _self_attr(e.value)
        if base is not None and cls is not None:
            t = self.m.attr_types.get((self.mod, cls), {}).get(base)
            if t and t in self.m.class_index:
                tmod, _ = self.m.class_index[t]
                write = isinstance(e.ctx, (ast.Store, ast.Del))
                self.m.accesses.append(_Access(
                    (tmod, t), e.attr, write, frozenset(guards),
                    self.rel, e.lineno, ctx.key))
            return
        # escaping bound method on an arbitrary object (obj.m passed as arg)
        if as_arg and isinstance(e.ctx, ast.Load) \
                and isinstance(e.value, ast.Name):
            self.m.escapes.add(e.attr)

    def _visit_call(self, call: ast.Call, ctx: _Ctx, cls, guards: tuple,
                    meth: str) -> None:
        func = call.func
        target = _thread_target(call)
        if target is not None:
            tattr = _self_attr(target)
            if tattr is not None and cls is not None:
                self.m.thread_roots.add((self.mod, cls, tattr))
        # callee spec for identity propagation + lock-order
        spec = None
        if isinstance(func, ast.Name):
            if func.id in self.m.module_funcs.get(self.mod, ()):
                spec = ("mod", self.mod, func.id)
        elif isinstance(func, ast.Attribute):
            sattr = _self_attr(func)
            if sattr is not None and cls is not None:
                if sattr in self.m.class_methods.get((self.mod, cls), ()):
                    spec = ("self", self.mod, cls, sattr)
                else:
                    # calling a stored callable (self._probe()): the read
                    # of the attr is an access even though the callee is
                    # unknowable
                    self.m.accesses.append(_Access(
                        (self.mod, cls), sattr, False, frozenset(guards),
                        self.rel, func.lineno, ctx.key))
            elif not (isinstance(func.value, ast.Name)
                      and func.value.id == "self"):
                base = _self_attr(func.value)
                t = (self.m.attr_types.get((self.mod, cls), {}).get(base)
                     if base is not None and cls is not None else None)
                if t and t in self.m.class_index:
                    spec = ("class", t, func.attr)
                else:
                    spec = ("name", func.attr)
        if spec is not None:
            ctx.calls.append(spec)
            ctx.call_events.append((guards, spec, self.rel, call.lineno))
        # walk the callee's base object (records self.X reads in chains)
        if isinstance(func, ast.Attribute):
            self._visit_expr(func.value, ctx, cls, guards, meth)
        # arguments: escapes, lambdas, nested-def callbacks (classified in
        # the prescan)
        for arg in list(call.args) + [kw.value for kw in call.keywords]:
            self._visit_expr(arg, ctx, cls, guards, meth, as_arg=True)

    # populated per file in build_model / per function in _prescan_nested
    _nested_usage: typing.Dict[int, str]


def _phase_a(model: SyncModel, rel: str, tree: ast.Module) -> None:
    """Collect declarations: classes + methods, lock decls, module funcs."""
    mod = _modstem(rel)
    model.module_funcs.setdefault(mod, set())
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            model.module_funcs[mod].add(node.name)
        elif isinstance(node, ast.Assign):
            _maybe_module_lock(model, mod, rel, node)
        elif isinstance(node, ast.ClassDef):
            ckey = (mod, node.name)
            if node.name in model.class_index:
                model.warnings.append(Finding(
                    "sync-lock-order", "warning", f"{rel}:{node.lineno}",
                    f"class name {node.name!r} shadows "
                    f"{model.class_index[node.name][1]} in the sync scopes; "
                    f"attr-type resolution keeps the first"))
            else:
                model.class_index[node.name] = (mod, rel)
            methods = model.class_methods.setdefault(ckey, set())
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    methods.add(sub.name)
                    _collect_class_decls(model, mod, node.name, rel, sub)


def _collect_class_decls(model: SyncModel, mod: str, cls: str, rel: str,
                         fnode) -> None:
    """Lock declarations + attr types from ``self.X = ...`` assignments
    anywhere in the class (usually ``__init__``)."""
    params: typing.Dict[str, str] = {}
    args = fnode.args
    for a in list(args.posonlyargs) + list(args.args) + list(args.kwonlyargs):
        t = _ann_name(a.annotation)
        if t:
            params[a.arg] = t
    for node in ast.walk(fnode):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        attr = _self_attr(node.targets[0])
        if attr is None:
            continue
        v = node.value
        if isinstance(v, ast.Call):
            term = _terminal(v.func)
            if term in _FACTORY_KINDS:
                literal = None
                if v.args and isinstance(v.args[0], ast.Constant) \
                        and isinstance(v.args[0].value, str):
                    literal = v.args[0].value
                _register_lock(model, f"{mod}.{cls}.{attr}", term, True,
                               literal, rel, node.lineno,
                               owner=(mod, cls), attr=attr)
            elif term in _RAW_KINDS and _threading_rooted(v.func):
                _register_lock(model, f"{mod}.{cls}.{attr}", term, False,
                               None, rel, node.lineno,
                               owner=(mod, cls), attr=attr)
            elif term and term[:1].isupper():
                # constructor assignment: the best-effort attr type
                model.attr_types.setdefault((mod, cls), {})[attr] = term
        elif isinstance(v, ast.Name) and v.id in params:
            model.attr_types.setdefault((mod, cls), {})[attr] = params[v.id]


def _maybe_module_lock(model: SyncModel, mod: str, rel: str,
                       node: ast.Assign) -> None:
    if len(node.targets) != 1 or not isinstance(node.targets[0], ast.Name):
        return
    name = node.targets[0].id
    v = node.value
    if not isinstance(v, ast.Call):
        return
    term = _terminal(v.func)
    if term in _FACTORY_KINDS:
        literal = None
        if v.args and isinstance(v.args[0], ast.Constant) \
                and isinstance(v.args[0].value, str):
            literal = v.args[0].value
        _register_lock(model, f"{mod}.{name}", term, True, literal, rel,
                       node.lineno, owner=mod, attr=name)
    elif term in _RAW_KINDS and _threading_rooted(v.func):
        _register_lock(model, f"{mod}.{name}", term, False, None, rel,
                       node.lineno, owner=mod, attr=name)


def _threading_rooted(func: ast.expr) -> bool:
    """``threading.Lock`` / bare ``Lock`` (from-imported) both count."""
    if isinstance(func, ast.Name):
        return True
    return (isinstance(func, ast.Attribute)
            and isinstance(func.value, ast.Name)
            and func.value.id == "threading")


def _register_lock(model: SyncModel, lid: str, kind: str, factory: bool,
                   literal: typing.Optional[str], rel: str, lineno: int,
                   owner, attr: str) -> None:
    model.locks[lid] = _Lock(lid, kind, factory, literal, rel, lineno)
    if isinstance(owner, tuple):
        model.class_locks.setdefault(owner, {})[attr] = lid
    else:
        model.module_locks.setdefault(owner, {})[attr] = lid
    if not factory:
        factory_name = {"Lock": "make_lock", "RLock": "make_rlock",
                        "Condition": "make_condition"}.get(kind, "make_lock")
        model.warnings.append(Finding(
            "sync-lock-order", "warning", f"{rel}:{lineno}",
            f"lock {lid} is built directly on threading.{kind} — declare it "
            f"through homebrewnlp_tpu.sync.{factory_name}({lid!r}) so the "
            f"HBNLP_SYNC_RECORD recorder can see it"))
    elif literal is not None and literal != lid:
        model.warnings.append(Finding(
            "sync-lock-order", "warning", f"{rel}:{lineno}",
            f"declared lock name {literal!r} does not match its derived "
            f"graph id {lid!r}; recorder edges will not line up with the "
            f"static graph"))


def build_model(root: str) -> SyncModel:
    model = SyncModel()
    files = []
    for path, rel in _iter_py_files(root, SYNC_SCOPES):
        norm = rel.replace(os.sep, "/")
        if any(norm == e or norm.startswith(e + "/") for e in SYNC_EXEMPT):
            continue
        src = open(path).read()
        tree = ast.parse(src, filename=rel)
        model.lines[norm] = src.splitlines()
        files.append((norm, tree))
    for rel, tree in files:
        _phase_a(model, rel, tree)
    for rel, tree in files:
        sc = _FileScanner(model, rel, tree)
        sc._nested_usage = {}
        sc.scan()
    _finalize(model)
    return model


def _finalize(model: SyncModel) -> None:
    """Seed thread/callback identities found during the scan, then
    propagate identities over the resolvable call graph to a fixpoint."""
    for (mod, cls, meth) in model.thread_roots:
        key = (mod, cls, meth)
        ctx = model.ctxs.get(key)
        if ctx is None:
            continue
        ctx.seeds.discard("caller")
        ctx.seeds.add(f"thread:{cls}.{meth}" if cls else f"thread:{meth}")
    for (mod, cls), methods in model.class_methods.items():
        for mname in methods & model.escapes:
            key = (mod, cls, mname)
            ctx = model.ctxs.get(key)
            if ctx is not None and (mod, cls, mname) not in model.thread_roots:
                ctx.seeds.add(f"callback:{cls}.{mname}")
    for ctx in model.ctxs.values():
        ctx.ids = set(ctx.seeds)
    changed = True
    while changed:
        changed = False
        for ctx in model.ctxs.values():
            if not ctx.ids:
                continue
            for spec in ctx.calls:
                for tgt in _resolve_spec(model, spec, identity=True):
                    t = model.ctxs.get(tgt)
                    if t is not None and not ctx.ids <= t.ids:
                        t.ids |= ctx.ids
                        changed = True
    _build_edges(model)


#: stdlib container/IO/threading method names the name-based call fallback
#: never fans out to — they would wire ``q.put(...)`` to every scanned
#: method of the same name.  Domain verbs (render, alloc, inc, close, ...)
#: stay in; ``graftsync --validate`` catches any edge this filter hides.
_NAME_FALLBACK_SKIP = frozenset({
    "append", "appendleft", "pop", "popleft", "get", "get_nowait", "put",
    "put_nowait", "set", "is_set", "clear", "wait", "notify", "notify_all",
    "acquire", "release", "locked", "join", "start", "items", "keys",
    "values", "update", "setdefault", "copy", "extend", "insert", "sort",
    "index", "write", "read", "readline", "flush", "seek", "encode",
    "decode", "split", "strip", "format", "startswith", "endswith",
    "lower", "upper", "replace", "remove", "discard",
    # subprocess.run / file.close would wire into Thread.run bodies and
    # every scanned close() method; direct x.run() calls are not a pattern
    # here (threads go through start()) and lock-holding close() calls on
    # scanned objects do not occur (the recorder validation would flag one)
    "run", "close"})


def _resolve_spec(model: SyncModel, spec: tuple, identity: bool
                  ) -> typing.List[tuple]:
    """Callee contexts for a call spec.  Identity propagation uses only the
    precisely-resolvable kinds; the lock-order closure additionally expands
    ``name`` specs to every scanned method of that name (over-approximation
    keeps the static graph a superset of what the recorder can observe)."""
    kind = spec[0]
    if kind == "self":
        _, mod, cls, m = spec
        return [(mod, cls, m)]
    if kind == "mod":
        _, mod, f = spec
        return [(mod, None, f)]
    if kind == "class":
        _, t, m = spec
        loc = model.class_index.get(t)
        if loc and m in model.class_methods.get((loc[0], t), ()):
            return [(loc[0], t, m)]
        return []
    if kind == "name" and not identity:
        _, m = spec
        if m in _NAME_FALLBACK_SKIP:
            return []
        out = []
        for (mod, cls), methods in model.class_methods.items():
            if m in methods:
                out.append((mod, cls, m))
        for mod, funcs in model.module_funcs.items():
            if m in funcs:
                out.append((mod, None, m))
        return out
    return []


def _build_edges(model: SyncModel) -> None:
    """Direct nesting edges + transitive call-while-holding edges."""
    # total acquire sets to a fixpoint (cycles in the call graph are fine)
    total: typing.Dict[tuple, typing.Set[str]] = {
        k: set(c.direct_locks) for k, c in model.ctxs.items()}
    changed = True
    while changed:
        changed = False
        for key, ctx in model.ctxs.items():
            acc = total[key]
            before = len(acc)
            for spec in ctx.calls:
                for tgt in _resolve_spec(model, spec, identity=False):
                    acc |= total.get(tgt, set())
            if len(acc) != before:
                changed = True

    def add(a: str, b: str, loc: str) -> None:
        if a != b:
            model.edges.setdefault((a, b), set()).add(loc)

    for ctx in model.ctxs.values():
        for held, lid, rel, line in ctx.acquires:
            for h in held:
                add(h, lid, f"{rel}:{line}")
        for held, spec, rel, line in ctx.call_events:
            if not held:
                continue
            for tgt in _resolve_spec(model, spec, identity=False):
                for lid in total.get(tgt, ()):
                    for h in held:
                        add(h, lid, f"{rel}:{line}")


# -- shared-state evaluation ---------------------------------------------

def shared_state_report(model: SyncModel) -> typing.List[dict]:
    by_attr: typing.Dict[tuple, typing.List[_Access]] = {}
    for acc in model.accesses:
        if acc.attr in model.class_locks.get(acc.owner, {}):
            continue  # the lock attrs themselves
        by_attr.setdefault((acc.owner, acc.attr), []).append(acc)
    out: typing.List[dict] = []
    for (owner, attr), accs in sorted(by_attr.items(),
                                      key=lambda kv: (kv[0][0], kv[0][1])):
        live = []
        for a in accs:
            ctx = model.ctxs.get(a.ctx)
            ids = {i for i in (ctx.ids if ctx else ())
                   if i != "init"}
            if ids:
                live.append((a, ids))
        if not live:
            continue
        all_ids = set().union(*[ids for _, ids in live])
        writes = [(a, ids) for a, ids in live if a.write]
        if not writes:
            continue
        if len(all_ids) < 2 or not any(
                i.startswith(("thread:", "callback:")) for i in all_ids):
            continue
        writer_ids = set().union(*[ids for _, ids in writes])
        need = [(a, ids) for a, ids in live
                if a.write or len(writer_ids) != 1 or not ids <= writer_ids]
        if not need:
            continue
        mod, cls = owner
        own = (set(model.class_locks.get(owner, {}).values())
               | set(model.module_locks.get(mod, {}).values()))
        counts: typing.Dict[str, int] = {}
        for a, _ in need:
            for lid in a.guards & own:
                counts[lid] = counts.get(lid, 0) + 1
        best = max(counts, key=counts.get) if counts else None
        viol = []
        for a, ids in need:
            if best is not None and best in a.guards:
                continue
            lines = model.lines.get(a.rel, ())
            if _suppressed(lines, a.lineno, "sync-shared-state"):
                continue
            viol.append({"file": a.rel, "line": a.lineno,
                         "write": a.write, "identities": sorted(ids)})
        if not viol:
            continue
        rel = model.class_index.get(cls, (None, "?"))[1]
        out.append({"key": f"{rel}::{cls}.{attr}", "class": cls,
                    "attr": attr, "lock": best,
                    "identities": sorted(all_ids), "sites": viol})
    return out


def shared_state_counts(root: str) -> typing.Dict[str, int]:
    model = build_model(root)
    return {r["key"]: len(r["sites"]) for r in shared_state_report(model)}


def sync_shared_state_golden_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "goldens", "sync", "shared_state.json")


def check_shared_state(root: str, update_goldens: bool = False
                       ) -> typing.List[Finding]:
    return _check_ratchet(
        "sync-shared-state", shared_state_counts(root),
        sync_shared_state_golden_path(), update_goldens,
        unit="unguarded multi-thread access(es)",
        over_hint="an attribute reached from >=2 thread identities with an "
                  "unlocked write is a data race; guard every access with "
                  "the owning class's declared lock (see docs/"
                  "static_analysis.md#concurrency-audit)")


# -- lock-order evaluation ------------------------------------------------

def lock_order_edges(root: str) -> typing.Dict[str, typing.List[str]]:
    model = build_model(root)
    return {f"{a} -> {b}": sorted(locs)
            for (a, b), locs in model.edges.items()}


def sync_lock_order_golden_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "goldens", "sync", "lock_order.json")


def _find_cycles(edges: typing.Iterable[typing.Tuple[str, str]]
                 ) -> typing.List[typing.List[str]]:
    """Strongly-connected components with >1 node (self-edges are already
    excluded at construction): each is a potential deadlock cycle."""
    adj: typing.Dict[str, typing.List[str]] = {}
    for a, b in edges:
        adj.setdefault(a, []).append(b)
        adj.setdefault(b, [])
    index: typing.Dict[str, int] = {}
    low: typing.Dict[str, int] = {}
    on: typing.Set[str] = set()
    stack: typing.List[str] = []
    out: typing.List[typing.List[str]] = []
    counter = [0]

    def strong(v: str) -> None:
        work = [(v, iter(adj[v]))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on.add(w)
                    work.append((w, iter(adj[w])))
                    advanced = True
                    break
                if w in on:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                scc = []
                while True:
                    w = stack.pop()
                    on.discard(w)
                    scc.append(w)
                    if w == node:
                        break
                if len(scc) > 1:
                    out.append(sorted(scc))

    for v in sorted(adj):
        if v not in index:
            strong(v)
    return out


def check_lock_order(root: str, update_goldens: bool = False
                     ) -> typing.List[Finding]:
    model = build_model(root)
    findings = list(model.warnings)
    edges = {f"{a} -> {b}": sorted(locs)
             for (a, b), locs in model.edges.items()}
    for cyc in _find_cycles(model.edges):
        findings.append(Finding(
            "sync-lock-order", "error", "lock-order graph",
            f"lock acquisition cycle (potential deadlock): "
            f"{' -> '.join(cyc)} -> {cyc[0]} — break it by ordering the "
            f"acquisitions or moving the inner call outside the lock"))
    path = sync_lock_order_golden_path()
    if update_goldens:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as f:
            json.dump({"edges": sorted(edges)}, f, indent=2)
            f.write("\n")
        findings.append(Finding(
            "sync-lock-order", "info", path,
            f"lock-order golden updated ({len(edges)} edge(s), "
            f"{len(model.locks)} declared lock(s))"))
        return findings
    if not os.path.exists(path):
        findings.append(Finding(
            "sync-lock-order", "error", path,
            "no lock-order golden; run --update-goldens"))
        return findings
    golden = set(json.load(open(path)).get("edges", []))
    for e in sorted(set(edges) - golden):
        findings.append(Finding(
            "sync-lock-order", "error", edges[e][0],
            f"new lock-order edge {e} is not in the pinned golden — check "
            f"every other path between these locks uses the same order, "
            f"then re-record with --update-goldens"))
    for e in sorted(golden - set(edges)):
        findings.append(Finding(
            "sync-lock-order", "info", path,
            f"pinned lock-order edge {e} no longer occurs; re-record with "
            f"--update-goldens"))
    return findings


def run_sync_rules(root: str, update_goldens: bool = False
                   ) -> typing.List[Finding]:
    return (check_shared_state(root, update_goldens)
            + check_lock_order(root, update_goldens))


# -- runtime validation ---------------------------------------------------

def validate_recorded(root: str, records: typing.Iterable[dict]
                      ) -> typing.List[Finding]:
    """The graftspmd-style honesty check: every ``held -> acquired`` edge
    the HBNLP_SYNC_RECORD shim logged while the real suites ran must appear
    in the static graph; a missed edge means the model lied.  Held-while-
    blocking events are informational (contention is legal; order is what
    deadlocks), held-while-joining is a warning (shutdown hazard)."""
    model = build_model(root)
    static = {f"{a} -> {b}" for (a, b) in model.edges}
    known = set(model.locks)
    findings: typing.List[Finding] = []
    seen_edges: typing.Set[typing.Tuple[str, str]] = set()
    n_blocked = n_joins = 0
    for rec in records:
        kind = rec.get("kind")
        if kind == "edge":
            seen_edges.add((rec["src"], rec["dst"]))
        elif kind == "blocked":
            n_blocked += 1
        elif kind == "join":
            n_joins += 1
            findings.append(Finding(
                "sync-lock-order", "warning", "recorded",
                f"Thread.join({rec.get('thread')!r}) while holding "
                f"{rec.get('held')} — the joined thread may need that lock "
                f"to exit; release before joining"))
    for src, dst in sorted(seen_edges):
        if src not in known or dst not in known:
            findings.append(Finding(
                "sync-lock-order", "error", "recorded",
                f"recorded edge {src} -> {dst} involves a lock the static "
                f"model does not know — a declaration outside the sync "
                f"factories or outside SYNC_SCOPES"))
        elif f"{src} -> {dst}" not in static:
            findings.append(Finding(
                "sync-lock-order", "error", "recorded",
                f"recorded lock-order edge {src} -> {dst} is MISSING from "
                f"the static graph — the model under-approximates reality; "
                f"extend analysis/concurrency.py resolution until it sees "
                f"this path"))
    findings.append(Finding(
        "sync-lock-order", "info", "recorded",
        f"runtime validation: {len(seen_edges)} recorded edge(s) vs "
        f"{len(static)} static, {n_blocked} held-while-blocking event(s), "
        f"{n_joins} join-with-held event(s)"))
    return findings
