"""graftcheck: static analysis pinning parallelism/dtype/sharding invariants.

The reference framework keeps pod-scale graphs correct through deterministic
naming and mtf's named-dim algebra; this JAX port re-derives those invariants
dynamically at trace time, so a bad ``PartitionSpec``, a silent f32->f64
promotion, or a dropped ``donate_argnums`` historically only surfaced as a
slow or OOMing TPU run.  This package is the correctness gate that catches
them BEFORE compilation, on CPU, in seconds:

- :mod:`~homebrewnlp_tpu.analysis.trace` abstractly traces the train / eval /
  decode steps of a config (``jax.jit(...).trace`` over ShapeDtypeStructs —
  no FLOPs, no XLA compile) and exposes the jaxprs plus donation metadata.
- :mod:`~homebrewnlp_tpu.analysis.graph_rules` runs rule passes over those
  jaxprs: collective census vs golden budgets, dtype-promotion audit,
  donation audit (train state AND the batch engine's pooled serving
  state), sharding-spec validation, constant-bloat check.
- :mod:`~homebrewnlp_tpu.analysis.spmd` propagates PartitionSpecs through
  the traced jaxprs to census the IMPLICIT collectives GSPMD inserts
  (ratcheted per-config goldens, conflicting-sharding lint, and an HLO
  cross-validation mode that pins the prediction against the actually
  compiled partitioned module).
- :mod:`~homebrewnlp_tpu.analysis.ast_rules` lints the source tree for the
  ``NT`` named-axis discipline: axis literals against the nd registry,
  ``.x`` escape ratchet, Python-side RNG/time in traced code,
  ``PartitionSpec`` literals naming unknown mesh axes, the host-sync
  ratchet (no blocking device->host reads inside the async train loop),
  the obs-in-trace ratchet (no span/registry observability calls inside
  jit-traced code), and the bare-io ratchet (no unwrapped open()/orbax
  storage calls in the train/data hot paths — everything routes through
  the reliability retry layer).

Entry point: ``python tools/graftcheck.py --all-configs`` (see
docs/static_analysis.md).
"""
from .findings import Finding, Severity, render_report, worst_severity  # noqa: F401
from .trace import ConfigTraces, trace_config  # noqa: F401
from .graph_rules import check_golden_coverage, run_graph_rules  # noqa: F401
from .ast_rules import run_ast_rules  # noqa: F401

GRAPH_RULES = ("collective-census", "dtype-promotion", "quant-dtype",
               "donation", "sharding-spec", "constant-bloat",
               "resource-budget", "implicit-collective", "mesh-rank")
# "dtype-promotion" appears in both: the AST pass carries its static twin
AST_RULES = ("axis-literal", "x-escape", "traced-rng", "partitionspec-axis",
             "dtype-promotion", "host-sync", "obs-in-trace", "bare-io",
             "sync-shared-state", "sync-lock-order")
# tree-wide gates (run once per --all-configs audit, not per config)
TREE_RULES = ("golden-coverage",)
ALL_RULES = tuple(dict.fromkeys(GRAPH_RULES + AST_RULES + TREE_RULES))
