"""Static per-device HBM accounting over abstractly-traced step jaxprs.

Everything here works on :class:`~homebrewnlp_tpu.analysis.trace.ConfigTraces`
— ShapeDtypeStructs and jaxprs, never allocated arrays — so predicting the
peak HBM of a billion-parameter config costs seconds on a CPU.  Components:

- **params / optimizer slots**: exact byte counts from the abstract param
  and slot shapes, divided per device by the sharding the intended
  (``tpu_size``) mesh would apply (``parallel/sharding.py`` rules).  On the
  1-chip CPU-traceable configs these match the analytic count exactly
  (pinned by tests/graftcost_test.py).
- **activation/residual live set**: a linear scan over equation liveness of
  the traced jaxpr — each equation-defined value is live from its defining
  equation to its last use; the peak of the running byte total is the
  transient-buffer estimate.  Sub-jaxprs (scan/pjit/while/custom_vjp bodies)
  are scanned recursively and their internal peak charged at the calling
  equation, which is how reversible blocks and remat show up as savings:
  their recompute lives inside the backward body instead of spanning the
  whole program.  Donated train-state outputs are excluded (they write into
  the donated input buffers — the donation rule pins that they stay
  donated).
- **sharding heuristic for activations**: a live buffer's per-device size
  divides by every intended mesh axis whose characteristic logical size
  (batch -> data, sequence -> sequence_parallel, heads -> model, stage ->
  pipeline) appears as one of its dimensions.  This is the idealized GSPMD
  placement; the tolerance recorded in each resources golden absorbs the
  approximation until TPU calibration tightens it.

The scan also returns the live set *at* the peak with each buffer
classified by how its dims scale in batch / sequence-length, which is what
lets ``tools/graftcost.py`` sweep context 1k -> 128k in milliseconds instead
of re-tracing every point.
"""
from __future__ import annotations

import dataclasses
import typing

import numpy as np

Aval = typing.Any


def aval_nbytes(aval) -> int:
    """Bytes of one abstract value (0 for abstract tokens/opaque avals)."""
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0
    try:
        itemsize = np.dtype(dtype).itemsize
    except TypeError:
        # extended dtypes (PRNG keys): itemsize from the key data layout
        itemsize = getattr(dtype, "itemsize", 4)
    n = 1
    for d in shape:
        try:
            n *= int(d)
        except TypeError:  # symbolic dim — count as 1, caller calibrates
            pass
    return int(n) * int(itemsize)


def _inner(jaxpr):
    return jaxpr.jaxpr if hasattr(jaxpr, "jaxpr") else jaxpr


def _sub_jaxprs(eqn) -> typing.Iterator:
    for v in eqn.params.values():
        vals = v if isinstance(v, (list, tuple)) else [v]
        for item in vals:
            if hasattr(item, "eqns") or (
                    hasattr(item, "jaxpr") and hasattr(item.jaxpr, "eqns")):
                yield item


@dataclasses.dataclass
class LivenessResult:
    peak_bytes: int
    #: avals live at the peak program point (top-level intermediates plus
    #: the live set of whichever sub-jaxpr was executing), for scaling
    #: classification — NOT a complete allocation trace
    peak_live: typing.List[Aval]


#: primitives XLA reliably fuses into their consumer/producer: their outputs
#: alias a buffer instead of materializing one.  The list is deliberately
#: conservative (pure elementwise + layout-only ops); anything absent
#: materializes, so omissions OVER-estimate peak rather than hide it.
FUSIBLE_PRIMS = frozenset((
    "add", "sub", "mul", "div", "neg", "max", "min", "rem", "pow",
    "integer_pow", "exp", "log", "log1p", "expm1", "tanh", "logistic",
    "sqrt", "rsqrt", "cbrt", "abs", "sign", "floor", "ceil", "round",
    "erf", "erf_inv", "erfc", "sin", "cos", "clamp", "select_n",
    "convert_element_type", "stop_gradient", "transpose", "reshape",
    "squeeze", "expand_dims", "rev", "copy", "and", "or", "xor", "not",
    "eq", "ne", "lt", "le", "gt", "ge", "is_finite", "square",
    "broadcast_in_dim", "broadcast", "iota", "real", "imag",
))


def liveness_peak(jaxpr, exclude_outputs: bool = False,
                  exclude_output_indices: typing.Optional[
                      typing.Set[int]] = None) -> LivenessResult:
    """Fusion-aware linear-scan liveness over one (Closed)Jaxpr: the peak
    simultaneous byte total of equation-defined buffers.

    Outputs of :data:`FUSIBLE_PRIMS` equations that are no larger than
    their largest equation-defined operand *alias* that operand's buffer
    (XLA fuses the elementwise chain; counting every norm/scale/activation
    intermediate separately over-predicted ~5x on the CPU-compilable
    configs).  Everything else materializes.  ``exclude_outputs`` models
    donated-buffer reuse: the jaxpr's own output vars (the new TrainState
    of a donated train step) are written into the donated argument buffers,
    so they only count while a later equation still reads them.
    ``exclude_output_indices`` excludes individual outvar positions the
    caller accounts as persistent state elsewhere (the KV caches a prefill
    writes).  Inputs and consts are never counted here — the caller
    accounts params, slots, batch and caches as persistent state.
    """
    inner = _inner(jaxpr)
    eqns = list(inner.eqns)
    n = len(eqns)

    # pass 1: aliasing (var id -> root buffer id) + per-root last use
    root: typing.Dict[int, int] = {}

    def find(vid: int) -> int:
        while vid in root:
            vid = root[vid]
        return vid

    defined_ids = set()
    last_use: typing.Dict[int, int] = {}
    def_site: typing.Dict[int, int] = {}
    root_aval: typing.Dict[int, Aval] = {}
    for i, eqn in enumerate(eqns):
        for v in eqn.invars:
            if hasattr(v, "aval") and not hasattr(v, "val"):  # skip Literals
                r = find(id(v))
                if r in defined_ids:
                    last_use[r] = i
        fusible = eqn.primitive.name in FUSIBLE_PRIMS
        # the largest equation-DEFINED operand this output may alias
        # (aliasing a jaxpr input would hide the buffer entirely — inputs
        # are accounted by the caller as persistent state)
        host = None
        if fusible:
            best = -1
            for v in eqn.invars:
                if not hasattr(v, "aval") or hasattr(v, "val"):
                    continue
                r = find(id(v))
                if r in defined_ids:
                    b = aval_nbytes(root_aval.get(r))
                    if b > best:
                        best, host = b, r
        for v in eqn.outvars:
            aval = getattr(v, "aval", None)
            if aval is None:
                continue
            defined_ids.add(id(v))
            if (host is not None
                    and aval_nbytes(aval) <= aval_nbytes(root_aval[host])):
                root[id(v)] = host  # fused: rides the operand's buffer
            else:
                def_site[id(v)] = i
                root_aval[id(v)] = aval
    for idx, v in enumerate(inner.outvars):
        if hasattr(v, "aval") and not hasattr(v, "val"):
            excluded = exclude_outputs or (
                exclude_output_indices is not None
                and idx in exclude_output_indices)
            r = find(id(v))
            if r in defined_ids and not excluded:
                last_use[r] = n  # live past the last equation

    # pass 2: the sweep over materializing roots
    live_bytes = 0
    live: typing.Dict[int, Aval] = {}
    peak = 0
    peak_live: typing.List[Aval] = []
    for i, eqn in enumerate(eqns):
        for v in eqn.outvars:
            vid = id(v)
            if def_site.get(vid) != i:
                continue  # aliased (or aval-less) — allocates nothing
            if vid not in last_use:
                continue  # dead value (DropVar/unused) — XLA elides it
            live[vid] = root_aval[vid]
            live_bytes += aval_nbytes(root_aval[vid])
        # sub-jaxpr internal peak is charged while this equation runs;
        # scan/while bodies execute one iteration at a time, so their
        # internal transient set does not multiply by trip count
        sub_peak, sub_live = 0, []
        for sub in _sub_jaxprs(eqn):
            r = liveness_peak(sub)
            if r.peak_bytes > sub_peak:
                sub_peak, sub_live = r.peak_bytes, r.peak_live
        if live_bytes + sub_peak > peak:
            peak = live_bytes + sub_peak
            peak_live = list(live.values()) + list(sub_live)
        # release roots whose last use was this equation
        touched = {find(id(v)) for v in eqn.invars
                   if hasattr(v, "aval") and not hasattr(v, "val")}
        touched.update(find(id(v)) for v in eqn.outvars
                       if hasattr(v, "aval"))
        for r in touched:
            if r in live and last_use.get(r, -1) <= i:
                live_bytes -= aval_nbytes(live.pop(r))
    return LivenessResult(int(peak), peak_live)


# -- sharding-aware per-device division -------------------------------------

def sharded_fraction(axis_names: typing.Sequence[str], imesh) -> float:
    """1 / (product of intended-mesh axis sizes this parameter shards
    over), via the same spec_for rules the real placement uses."""
    from ..parallel.sharding import spec_for
    spec = spec_for(tuple(axis_names), imesh)
    denom = 1
    for part in spec:
        for ax in (part if isinstance(part, tuple) else (part,)):
            if ax is not None:
                denom *= int(imesh.shape.get(ax, 1))
    return 1.0 / max(1, denom)


def activation_divisor(shape: typing.Sequence[int], cfg, imesh) -> int:
    """Idealized GSPMD divisor for one activation buffer: each intended
    mesh axis (>1) divides the buffer once if its characteristic logical
    size appears among the dims.  Heuristic — jaxpr vars carry no axis
    names — recorded as such in docs/static_analysis.md."""
    from ..parallel.mesh import DATA_AXIS, MODEL_AXIS, PIPE_AXIS, SEQ_AXIS
    n_micro = max(1, cfg.grad_accumulation * cfg.macro_batching)
    batch_sizes = {cfg.train_batch_size,
                   cfg.train_batch_size * cfg.macro_batching,
                   max(1, cfg.train_batch_size // n_micro)}
    seq_sizes = {cfg.sequence_length, cfg.time_patch_size,
                 cfg.language_token_patch}
    char = {DATA_AXIS: batch_sizes, SEQ_AXIS: seq_sizes,
            MODEL_AXIS: {cfg.heads}, PIPE_AXIS: {cfg.pipeline_parallel}}
    dims = [int(d) for d in shape]
    divisor = 1
    for axis, sizes in char.items():
        size = int(imesh.shape.get(axis, 1))
        if size <= 1:
            continue
        hit = next((i for i, d in enumerate(dims) if d in sizes and d > 1),
                   None)
        if hit is not None:
            dims.pop(hit)  # one mesh axis per matched dim
            divisor *= size
    return divisor


# -- scaling classification (for the graftcost sweep) ------------------------

@dataclasses.dataclass
class ScaledBytes:
    """Bytes at the traced anchor plus integer scaling exponents in
    sequence length and batch: ``bytes(b, s) = bytes0 * (b/b0)**batch_exp
    * (s/s0)**seq_exp``.  An attention-map logit buffer [batch, heads, s, s]
    classifies as seq_exp=2 — the quadratic term long-context planning
    cares about."""
    bytes0: float
    seq_exp: int = 0
    batch_exp: int = 0

    def at(self, batch_ratio: float, seq_ratio: float) -> float:
        return (self.bytes0 * (batch_ratio ** self.batch_exp)
                * (seq_ratio ** self.seq_exp))


def classify_shape(shape: typing.Sequence[int], nbytes: float, cfg
                   ) -> ScaledBytes:
    """Classify one buffer's dims against the config's anchor sizes.
    Sequence matches win over batch on ambiguous dims (long-context sweeps
    are the primary consumer); anchors with batch == seq are flagged by the
    caller."""
    n_micro = max(1, cfg.grad_accumulation * cfg.macro_batching)
    seq_sizes = {cfg.sequence_length, cfg.time_patch_size,
                 cfg.language_token_patch}
    batch_sizes = {cfg.train_batch_size,
                   cfg.train_batch_size * cfg.macro_batching,
                   max(1, cfg.train_batch_size // n_micro)}
    seq_exp = batch_exp = 0
    for d in shape:
        d = int(d)
        if d > 1 and d in seq_sizes:
            seq_exp += 1
        elif d > 1 and d in batch_sizes:
            batch_exp += 1
    return ScaledBytes(float(nbytes), seq_exp=seq_exp, batch_exp=batch_exp)


def sum_scaled(components: typing.Iterable[ScaledBytes],
               batch_ratio: float, seq_ratio: float) -> float:
    return sum(c.at(batch_ratio, seq_ratio) for c in components)
