"""Top-level model assembly: input -> body -> output -> loss.

Mirrors the reference's build pipeline (/root/reference/src/model/__init__.py:
_input :32-91, _body :94-130, _output :133-156, _loss :159-200, build :231-259)
re-designed for JAX: the "graph build" is tracing, memory-reduction strategies
map to jax.checkpoint / custom_vjp reversible chains, and all parallelism is
deferred to sharding constraints applied by the caller (parallel/apply.py).
"""
from __future__ import annotations

import logging
import typing

import jax
import jax.numpy as jnp

from .. import nd
from ..config import (BATCH, COLOR_CHANNELS, Config, HEADS, HEIGHT, INTERMEDIATE,
                      KEY, SEQUENCE, TOKEN_PATCH, VOCAB, WIDTH)
from ..nd import NT
from ..ops.losses import accuracy as _accuracy_fn
from ..ops.losses import softmax_cross_entropy_with_logits, video_l1_loss
from ..ops.reversible import make_reversible_chain
from .ctx import Args, Ctx, DEPTH_TOKEN
from .embedding import embed, gather, gather_embed, positional_embed
from .linear import linear, linear_from_features, linear_to_features
from .registry import block_part_fn


class ModelOutput(typing.NamedTuple):
    loss: jnp.ndarray
    loss_list: typing.Tuple[jnp.ndarray, ...]
    video_loss: typing.Optional[jnp.ndarray]
    accuracy: typing.Optional[jnp.ndarray]
    token_loss: typing.Optional[jnp.ndarray]
    frame_out: typing.Optional[NT]
    token_out: typing.Optional[NT]


# -- input ------------------------------------------------------------------

def _input(ctx: Ctx, batch: typing.Dict[str, NT], spatial_ctx: str
           ) -> typing.Tuple[NT, typing.Optional[NT]]:
    cfg = ctx.cfg
    tgt = None
    src = None
    if cfg.use_video:
        vid = batch["frame"].astype(cfg.calculation_dtype)
        base_args = Args(ctx, vid, [""])
        vid = ctx.dropout(vid, cfg.input_dropout)
        if cfg.use_bit_fold_input_pipeline:
            # unpack fold_count low-bit color values per packed int
            # (reference src/model/__init__.py:45-56); uint32 keeps all 32
            # packed bits without requiring jax x64
            folded = vid.x.astype(jnp.uint32)
            parts = []
            for unfold_idx in range(cfg.fold_count):
                part = (folded // (2 ** cfg.bit_fold_value) ** unfold_idx
                        ) % (2 ** cfg.bit_fold_value)
                parts.append(part.astype(jnp.uint8))
            vid = NT(jnp.concatenate(parts, vid.names.index(COLOR_CHANNELS)),
                     vid.names)
        vid = vid.astype(cfg.calculation_dtype) / 255
        ctx_dim = vid.names[1]  # "_sequence", length seq+1
        n = vid.dim_size(ctx_dim)
        tgt = nd.nt_slice(vid, ctx_dim, 1, n).rename(ctx_dim, SEQUENCE)
        src = nd.nt_slice(vid, ctx_dim, 0, n - 1).rename(ctx_dim, SEQUENCE)

        if cfg.empty_frame_embedding is not None:
            embed_args = base_args(list(cfg.empty_frame_embedding))
            frame_dims = [(name, src.dim_size(name)) for name in src.names[2:]]
            empty = embed(embed_args, frame_dims)
            for msk_name in ("vid_msk_src", "cat_mask_x"):
                msk = batch.get(msk_name)
                if msk is not None:
                    m = msk.astype(cfg.calculation_dtype)
                    src = src * m + empty * (1 - m)

        src = linear_to_features(base_args(src),
                                 [(COLOR_CHANNELS, src.dim_size(COLOR_CHANNELS))])
        for config_idx, config in enumerate(cfg.input_block_config):
            src = block_part_fn(ctx, config, src, f"vid_inp{config_idx}")

    if cfg.use_language:
        txt_src = batch["token_x"]
        base_args = Args(ctx, txt_src, [""])
        small = int(cfg.intermediate_size * cfg.vocab_weight_factorization)
        txt, table = gather_embed(base_args(list(cfg.token_embedding)),
                                  [(VOCAB, cfg.vocab_size), (INTERMEDIATE, small)])
        ctx.text_input_embedding = table
        txt = ctx.dropout(txt, cfg.input_dropout)
        txt = linear_to_features(
            base_args(txt), [(TOKEN_PATCH, cfg.token_patch_size), (INTERMEDIATE, small)])
        for config_idx, config in enumerate(cfg.input_block_config):
            txt = block_part_fn(ctx, config, txt, f"lang_inp{config_idx}")
        if not cfg.use_video:
            return txt, tgt
        return nd.concat([src, txt], spatial_ctx), tgt
    return src, tgt


# -- body -------------------------------------------------------------------

def _attn_layers(conf) -> int:
    return sum(l.split("-")[0] == "attention" for l in conf.layer)


def _block_scope(i: int, c: int) -> str:
    return f"{DEPTH_TOKEN}{i}_{c}"


def _block_param_keys(all_keys, root: str, i: int, c: int,
                      include_shared: bool = True) -> typing.List[str]:
    """Param keys of the (depth i, config c) block group.  ``include_shared``
    adds the cross-depth shared_{c} tensors (reference backend.py:43-94) —
    the stack/unstack transforms exclude them (they are replicated per stage
    instead, see stack_pipeline_params), while the pipelined body's slot
    dicts include them."""
    p1 = f"{root}/{_block_scope(i, c)}/"
    p2 = f"{root}/shared_{c}/"
    return sorted(k for k in all_keys
                  if k.startswith(p1) or (include_shared and k.startswith(p2)))


def _body(ctx: Ctx, src: NT) -> NT:
    cfg = ctx.cfg
    with ctx.scope("body"):
        if cfg.use_initial_position_embedding:
            base_args = Args(ctx, src, [""])
            for dim in [n for n in src.names if n not in cfg.feature_dims][1:]:
                fdims = [(n, cfg.dims[n]) for n in cfg.feature_dims]
                src = src + positional_embed(
                    base_args(list(cfg.position_embedding)), dim,
                    src.dim_size(dim), fdims)

        strategy = cfg.memory_reduction_strategy
        seq = [(i, c) for i in range(cfg.depth) for c in range(len(cfg.block_config))]
        attn_starts = []
        acc = ctx.attention_idx
        for i, c in seq:
            attn_starts.append(acc)
            acc += _attn_layers(cfg.block_config[c])

        if ctx.params is None or ctx.decode is not None:
            # init / collect mode: run the plain chain so parameters
            # materialize.  KV-cache decode takes the same path: there is no
            # backward pass so the memory-reduction machinery (which rebuilds
            # per-block sub-Ctxs that would drop the decode state) is skipped,
            # while the scope walk — and therefore every parameter path —
            # stays identical.
            if strategy in ("revnet", "momentum"):
                x1, x2 = (src, src) if strategy == "revnet" else (src, nd.zeros_like(src))
                for k, (i, c) in enumerate(seq):
                    ctx.attention_idx = attn_starts[k]
                    with ctx.scope(_block_scope(i, c)):
                        fx = block_part_fn(
                            ctx, cfg.block_config[c],
                            x2 if strategy == "revnet" else x1)
                    if strategy == "revnet":
                        x1, x2 = x2, x1 + fx
                    else:
                        x2 = x2 * cfg.momentumnet_alpha + fx * (1 - cfg.momentumnet_alpha)
                        x1 = x1 + x2
                ctx.attention_idx = acc
                return x1 + x2
            out = src
            for k, (i, c) in enumerate(seq):
                ctx.attention_idx = attn_starts[k]
                with ctx.scope(_block_scope(i, c)):
                    out = block_part_fn(ctx, cfg.block_config[c], out)
            ctx.attention_idx = acc
            return out

        if cfg.pipeline_parallel > 1 and ctx.mesh is not None:
            return _pipelined_body(ctx, src, seq, attn_starts, acc)

        # apply mode: each block runs in its own Ctx over a param subdict so
        # the reversible chain can take explicit per-block parameters.
        mode_scope = ctx._scope[0]
        root = f"{mode_scope}/body"
        all_keys = list(ctx.params.keys())

        def keys_for(i: int, c: int) -> typing.List[str]:
            return _block_param_keys(all_keys, root, i, c)

        def make_f(k: int, i: int, c: int, with_aux: bool = False):
            conf = cfg.block_config[c]
            a_start = attn_starts[k]
            rng = None if ctx.rng is None else jax.random.fold_in(ctx.rng, 1000 + k)

            def f(subparams: dict, x: NT):
                bctx = Ctx(cfg, params=subparams, train=ctx.train, seed=ctx.seed,
                           rng=rng, mesh=ctx.mesh)
                bctx.attention_idx = a_start
                with bctx.preset_scope(mode_scope, "body"), \
                        bctx.scope(_block_scope(i, c)):
                    out = block_part_fn(bctx, conf, x)
                if with_aux:
                    # aux losses (routed-MoE balance term) returned as real
                    # outputs so they cross jax.checkpoint with gradients
                    # intact; the per-block count is static (set by the
                    # block's layer specs), so the pytree structure is stable
                    return out, tuple(bctx.aux_losses)
                return out

            return f

        ctx.attention_idx = acc
        subparams = tuple({k: ctx.params[k] for k in keys_for(i, c)} for i, c in seq)

        if strategy in ("revnet", "momentum"):
            # aux losses cannot cross the reversible custom_vjp boundary;
            # config validation rejects routed_moe here when
            # moe_balance_weight > 0 (config.py)
            fs = [make_f(k, i, c) for k, (i, c) in enumerate(seq)]
            cot = (jnp.dtype(cfg.reversible_cotangent_dtype)
                   if cfg.reversible_cotangent_dtype else None)
            # remat skips fused-kernel blocks: their custom_vjp already
            # stores only inputs, so jax.checkpoint there would re-run the
            # forward kernel for nothing (measured +30 ms on 32mixer_group)
            from .layers import fused_group_eligible, fused_mixer_eligible
            rb = [cfg.reversible_remat_blocks
                  and not fused_mixer_eligible(ctx, cfg.block_config[c], src)
                  and not fused_group_eligible(ctx, cfg.block_config[c], src)
                  for _, c in seq]
            chain = make_reversible_chain(fs, mode=strategy,
                                          alpha=cfg.momentumnet_alpha,
                                          cotangent_dtype=cot,
                                          remat_blocks=rb)
            if strategy == "revnet":
                y1, y2 = chain(subparams, src, src)
            else:
                y1, y2 = chain(subparams, src, nd.zeros_like(src))
            return y1 + y2
        fs = [make_f(k, i, c, with_aux=True) for k, (i, c) in enumerate(seq)]
        out = src
        for f, p in zip(fs, subparams):
            if strategy == "checkpoint":
                out, aux = jax.checkpoint(f)(p, out)
            else:
                out, aux = f(p, out)
            ctx.aux_losses.extend(aux)
        return out


def _pipelined_body(ctx: Ctx, src: NT, seq, attn_starts, acc) -> NT:
    """GPipe pipeline-parallel body (ops/pipeline.py): the depth loop is cut
    into ``cfg.pipeline_parallel`` contiguous stages living on the pipeline
    mesh axis; microbatches stream through with activations hopping stages
    via ppermute.  Config validation guarantees P divides depth, so one
    stage function — scoped with stage 0's parameter names — serves every
    stage with its own stacked weights; cross-depth 'shared' tensors ride
    as stage-replicated leaves (stack_pipeline_params) kept bit-synced by
    the stage-summed grad broadcast (sync_shared_pipeline_grads).

    Parameters arrive STAGE-STACKED (``stack_pipeline_params``): the flat
    dict holds one ``[P, ...]`` leaf per stage-0 group key, sharded over the
    pipeline mesh axis, so each device holds only its own stage's weights —
    and optimizer state — with no per-step gather."""
    from ..ops.pipeline import gpipe
    from ..parallel.mesh import PIPE_AXIS
    cfg = ctx.cfg
    # aux-carrying layers (routed-MoE balance): thread the aux-loss stream
    # through the forward so eval/build() reports the same total loss the
    # 1F1B training path optimizes
    needs_aux = cfg.moe_balance_weight > 0 and any(
        spec.split("-")[0] == "routed_moe"
        for blk in cfg.block_config
        for spec in (blk["layer"] if isinstance(blk, dict) else blk.layer))
    stage_fn, stacked, n_stages = _pipeline_machinery(
        cfg, ctx.params, src.names, ctx.rng, ctx.train, ctx.seed,
        seq, attn_starts, mode_scope=ctx._scope[0], with_aux=needs_aux,
        mesh=ctx.mesh)
    # match the training schedule's micro partition: for 1F1B configs the
    # balance loss and capacity-dropped tokens of routed-MoE layers depend on
    # M, so eval/build() must pick the same M the 1F1B training path picks
    # (largest divisor with >= 8 rows) rather than gpipe's smallest
    n_micro = _pipeline_n_micro(src.x.shape[0], n_stages,
                                cfg.pipeline_schedule)
    if needs_aux:
        y, aux_total = gpipe(stage_fn, stacked, src.x, n_stages, n_micro,
                             ctx.mesh, PIPE_AXIS, with_aux=True)
        ctx.aux_losses.append(aux_total)
    else:
        y = gpipe(stage_fn, stacked, src.x, n_stages, n_micro, ctx.mesh,
                  PIPE_AXIS)
    ctx.attention_idx = acc
    return NT(y, names=src.names)


def _pipeline_machinery(cfg: Config, params, names, rng, train, seed,
                        seq, attn_starts, mode_scope, with_aux=False,
                        mesh=None):
    """(stage_fn, stacked slot list, n_stages) shared by the GPipe forward
    body and the 1F1B loss-and-grad path.  ``stage_fn(slot_params, idx, x)``
    runs one stage's block groups on one microbatch; ``stacked`` is the
    per-group list of stage-stacked param dicts (shared leaves replicated,
    see stack_pipeline_params).

    ``with_aux`` (the 1F1B contract): stage_fn returns ``(y, aux_loss)``
    where aux_loss is the f32 sum of the stage's layer-collected auxiliary
    loss terms (routed-MoE balance) — threaded through jax.checkpoint as a
    real output, exactly like the sequential body does."""
    n_stages = cfg.pipeline_parallel
    n_groups = len(seq)
    assert n_groups % n_stages == 0
    g = n_groups // n_stages
    root = f"{mode_scope}/body"
    all_keys = list(params.keys())
    if not pipeline_params_stacked(cfg, params):
        raise ValueError(
            "pipelined body expects stage-stacked parameters "
            "(models.stack_pipeline_params) but found per-depth keys for "
            f"stage-1 group {_block_scope(*seq[g])!r}")
    stacked = []
    for j in range(g):
        i0, c0 = seq[j]
        # include_shared: the stage-replicated shared_{c} leaves ride into
        # every group slot of their config (same stacked leaf; autodiff sums
        # the per-use cotangents, sync_shared_pipeline_grads sums stages)
        keys = _block_param_keys(all_keys, root, i0, c0, include_shared=True)
        stacked.append({k: params[k] for k in keys})

    def make_block_f(j: int):
        i0, c0 = seq[j]
        conf = cfg.block_config[c0]

        def f(subparams: dict, x_nt: NT, stage_idx):
            key = None
            if rng is not None:
                key = jax.random.fold_in(
                    jax.random.fold_in(rng, 2000 + j), stage_idx)
            # mesh=None: constraint() cannot fire inside the manual pipe
            # region; outer_mesh carries the real axis sizes for the
            # eligibility checks and the nested ring-attention path
            bctx = Ctx(cfg, params=subparams, train=train, seed=seed,
                       rng=key, mesh=None, outer_mesh=mesh)
            bctx.attention_idx = attn_starts[j]
            with bctx.preset_scope(mode_scope, "body"), \
                    bctx.scope(_block_scope(i0, c0)):
                out = block_part_fn(bctx, conf, x_nt)
            if not with_aux:
                return out
            aux = jnp.float32(0.0)
            for a in bctx.aux_losses:
                aux = aux + a.astype(jnp.float32)
            return out, aux

        return f

    block_fs = [make_block_f(j) for j in range(g)]
    remat = cfg.memory_reduction_strategy == "checkpoint"

    def stage_fn(slot_params, stage_idx, x):
        out = NT(x, names)
        aux_total = jnp.float32(0.0)
        for j, f in enumerate(block_fs):
            run = jax.checkpoint(f, static_argnums=()) if remat else f
            if with_aux:
                out, aux = run(slot_params[j], out, stage_idx)
                aux_total = aux_total + aux
            else:
                out = run(slot_params[j], out, stage_idx)
        return (out.x, aux_total) if with_aux else out.x

    return stage_fn, stacked, n_stages


def _pipeline_n_micro(batch: int, n_stages: int,
                      schedule: str = "gpipe") -> int:
    """Ideal M >= P microbatches keeps every stage busy; fall back to the
    largest batch divisor below P (with partial bubble) rather than silently
    serializing the whole pipe.

    GPipe picks the SMALLEST such M (its autodiff residuals hold every
    microbatch's internals, so M only shrinks the bubble at no memory gain
    for a fixed batch).  1F1B picks the LARGEST M keeping >= 8 rows per
    microbatch: its stash holds 2P stage inputs TOTAL (so memory shrinks as
    2P/M of the batch) and the bubble fraction 2(P-1)/(M+2P-2) falls with
    M; the row floor keeps per-tick matmuls tile-friendly."""
    divisors = [d for d in range(1, batch + 1) if batch % d == 0]
    at_least_p = [d for d in divisors if d >= n_stages]
    if schedule == "1f1b":
        big = [d for d in at_least_p if batch // d >= 8]
        if big:
            return max(big)
    n_micro = min(at_least_p) if at_least_p else max(divisors)
    if n_micro < n_stages:
        logging.getLogger(__name__).warning(
            "batch %d yields only %d pipeline microbatches for %d stages "
            "— pipe utilization %d/%d", batch, n_micro, n_stages, n_micro,
            n_stages)
    return n_micro


def pipelined_loss_and_grads(cfg: Config, params, batch, rng, mesh,
                             seed: int = 0):
    """1F1B training path (``pipeline_schedule='1f1b'``): loss AND grads
    from one interleaved pipeline schedule (ops/pipeline.py::pipeline_1f1b).

    The model is cut at the body pipeline: the input layer (+ optional body
    position embedding) runs upstream under ordinary autodiff, the body's
    stage stack runs inside the schedule, and the output/loss tail runs ON
    THE LAST STAGE per microbatch — its vjp seeds each microbatch's
    backward, which is what makes the M-independent activation memory of
    1F1B possible at all (an outer ``jax.grad`` over a forward-only
    pipeline cannot interleave).  Scope walks replicate ``build()`` exactly
    (same parameter names); config validation restricts the tail to the
    plain language loss (no accuracy/contrastive) in v1.

    Returns ``(grads, ModelOutput)`` like ``Trainer._grads``."""
    from ..ops.pipeline import pipeline_1f1b
    from ..parallel.mesh import PIPE_AXIS

    seq, g = _pipeline_seq(cfg)
    attn_starts = []
    acc = 0
    for i, c in seq:
        attn_starts.append(acc)
        acc += _attn_layers(cfg.block_config[c])
    root = f"{cfg.model_mode}/body"
    all_keys = list(params.keys())
    stage_keys = set()
    for j in range(g):
        i0, c0 = seq[j]
        stage_keys.update(_block_param_keys(all_keys, root, i0, c0,
                                            include_shared=True))
    other = {k: v for k, v in params.items() if k not in stage_keys}
    spatial_ctx = batch["token_y"].names[-2]

    def upstream(other_params):
        ctx = Ctx(cfg, params=other_params, train=True, rng=rng, mesh=mesh,
                  seed=seed)
        with ctx.scope(cfg.model_mode):
            src, _ = ctx.scoped("input", _input, ctx, batch, spatial_ctx)
            with ctx.scope("body"):
                if cfg.use_initial_position_embedding:
                    base_args = Args(ctx, src, [""])
                    for dim in [n for n in src.names
                                if n not in cfg.feature_dims][1:]:
                        fdims = [(n, cfg.dims[n]) for n in cfg.feature_dims]
                        src = src + positional_embed(
                            base_args(list(cfg.position_embedding)), dim,
                            src.dim_size(dim), fdims)
        return src

    src_nt, up_vjp = jax.vjp(upstream, other)
    names = src_nt.names

    # thread the caller's Ctx seed (the same value build()/_losses uses, so
    # any seed-dependent apply-time behavior matches the eval walk)
    stage_fn, stacked, n_stages = _pipeline_machinery(
        cfg, params, names, rng, True, seed, seq, attn_starts,
        mode_scope=cfg.model_mode, with_aux=True, mesh=mesh)
    n_micro = _pipeline_n_micro(src_nt.x.shape[0], n_stages, "1f1b")

    batch_keys = sorted(batch.keys())
    batch_names = {k: batch[k].names for k in batch_keys}
    tail_arrays = tuple(batch[k].x for k in batch_keys)

    def tail_fn(other_params, y, *tail_micro):
        micro_batch = {k: NT(a, batch_names[k])
                       for k, a in zip(batch_keys, tail_micro)}
        ctx = Ctx(cfg, params=other_params, train=True, seed=seed,
                  rng=None if rng is None else jax.random.fold_in(rng, 3001))
        with ctx.scope(cfg.model_mode):
            frame_out, token_out = ctx.scoped(
                "output", _output, ctx, NT(y, names), spatial_ctx)
            loss_list, token_loss, acc, _ = ctx.scoped(
                "loss", _loss, ctx, frame_out, token_out, micro_batch, None)
        total = loss_list[0]
        for l in loss_list[1:]:
            total = total + l
        # per-microbatch metrics ride the schedule's aux stream (averaged
        # over microbatches by the op, like the loss)
        aux = {"token_loss": token_loss.x if hasattr(token_loss, "x")
               else token_loss}
        if acc is not None:
            aux["accuracy"] = acc.x if hasattr(acc, "x") else acc
        return total, aux

    loss, aux, dstacked, dtail, dsrc = pipeline_1f1b(
        stage_fn, tail_fn, stacked, other, src_nt.x, tail_arrays,
        n_stages, n_micro, mesh, PIPE_AXIS)
    (dother_up,) = up_vjp(NT(dsrc.astype(src_nt.dtype), names))

    grads = {}
    for slot in dstacked:
        for k, v in slot.items():
            # shared leaves appear in every group slot of their config;
            # their per-slot contributions sum (matching autodiff)
            grads[k] = v if k not in grads else grads[k] + v
    for k in other:
        # both dicts always carry every key (vjp and the schedule's grad
        # carry produce full pytrees with zero leaves for unused params)
        grads[k] = dother_up[k].astype(jnp.float32) + dtail[k]
    out = ModelOutput(loss, (loss,), None, aux.get("accuracy"),
                      aux.get("token_loss"), None, None)
    return grads, out


# -- output -----------------------------------------------------------------

def _output(ctx: Ctx, out: NT, spatial_ctx: str
            ) -> typing.Tuple[typing.Optional[NT], typing.Optional[NT]]:
    cfg = ctx.cfg
    base_args = Args(ctx, out, [""])
    token_out = frame_out = None
    contrastive = cfg.contrastive_across_samples or cfg.contrastive_across_token_embeddings

    if cfg.use_language:
        token_out = out
        if cfg.use_video:
            token_out = nd.nt_slice(out, spatial_ctx, 0, cfg.language_token_patch)
        for config_idx, config in enumerate(cfg.output_block_config):
            token_out = block_part_fn(ctx, config, token_out, f"lang_out{config_idx}")
        if not contrastive:
            old = [(n, cfg.dims[n]) for n in cfg.feature_dims]
            new = [(TOKEN_PATCH, cfg.token_patch_size), (VOCAB, cfg.vocab_size)]
            table = embed(base_args(list(cfg.output_embedding)), old + new)
            out_names = tuple(n for n in token_out.names if n not in cfg.feature_dims
                              ) + (TOKEN_PATCH, VOCAB)
            token_out = nd.einsum([token_out, table], out_names)

    if cfg.use_video:
        start = cfg.language_token_patch * cfg.use_language
        frame_out = nd.nt_slice(out, spatial_ctx, start, out.dim_size(spatial_ctx))
        for config_idx, config in enumerate(cfg.output_block_config):
            frame_out = block_part_fn(ctx, config, frame_out, f"vid_out{config_idx}")
        frame_out = linear_from_features(
            Args(ctx, frame_out, [""]),
            [(COLOR_CHANNELS, cfg.channel_color_size)])
        frame_out = NT(jax.nn.sigmoid(frame_out.x), frame_out.names)

    return frame_out, token_out


# -- loss -------------------------------------------------------------------

def _loss(ctx: Ctx, frame_out, token_out, batch, vid_tgt):
    cfg = ctx.cfg
    loss_list: typing.List[jnp.ndarray] = []
    token_loss = acc = video_loss = None
    if cfg.use_language:
        txt_tgt = batch["token_y"]
        if cfg.contrastive_across_samples or cfg.contrastive_across_token_embeddings:
            sq = nd.reduce_sum(token_out * token_out, reduced=list(cfg.feature_dims))
            token_out = token_out / NT(jnp.sqrt(sq.x), sq.names)
        if cfg.contrastive_across_samples:
            sum_samples = nd.reduce_sum(token_out, reduced=[SEQUENCE])
            sum_batch = nd.reduce_sum(token_out, reduced=[BATCH])
            t1 = nd.einsum([sum_batch, sum_batch], []).x / cfg.train_batch_size
            t2 = nd.einsum([sum_samples, sum_samples], []).x / cfg.sequence_length
            token_loss = (t1 - t2) / (cfg.train_batch_size * cfg.sequence_length)
            token_loss = token_loss.astype(jnp.float32)
        elif cfg.contrastive_across_token_embeddings:
            table = ctx.text_input_embedding
            token_loss = nd.einsum([token_out, table], []).x.astype(jnp.float32)
            gathered = gather(Args(ctx, txt_tgt, [""]), table, [HEADS])
            token_loss = token_loss - 2 * nd.einsum(
                [token_out, gathered], []).x.astype(jnp.float32)
            token_loss = token_loss / (token_out.size * cfg.vocab_size)
        else:
            token_loss = softmax_cross_entropy_with_logits(token_out, txt_tgt, cfg.z_loss)
            if cfg.calc_accuracy:
                acc = _accuracy_fn(token_out, txt_tgt)
        loss_list.append(token_loss)

    if cfg.use_video:
        vid_msk = batch.get("vid_msk_tgt")
        cat_msk = batch.get("cat_mask_y")
        vmsk = vid_msk.astype(jnp.float32) if vid_msk is not None else None
        cmsk = cat_msk.astype(jnp.float32) if cat_msk is not None else None
        train_vl, video_loss = video_l1_loss(frame_out, vid_tgt, vmsk, cmsk)
        loss_list.append(train_vl)

    return loss_list, token_loss, acc, video_loss


# -- top level --------------------------------------------------------------

def build(ctx: Ctx, batch: typing.Dict[str, NT]) -> ModelOutput:
    """Assemble the full model and return losses/outputs.

    ``batch`` maps input names (token_x/token_y/frame/...masks) to NTs,
    mirroring the reference input pipeline shapes (dataclass.py:310-337)."""
    cfg = ctx.cfg
    with ctx.scope(cfg.model_mode):
        if cfg.use_language:
            spatial_ctx = batch["token_y"].names[-2]
        else:
            spatial_ctx = batch["frame"].names[2]
        src, vid_tgt = ctx.scoped("input", _input, ctx, batch, spatial_ctx)
        out = _body(ctx, src)  # pushes its own "body" scope
        frame_out, token_out = ctx.scoped("output", _output, ctx, out, spatial_ctx)
        loss_list, token_loss, acc, video_loss = ctx.scoped(
            "loss", _loss, ctx, frame_out, token_out, batch, vid_tgt)
        if ctx.aux_losses:
            # layer-collected auxiliary terms (routed-MoE load balance)
            aux = ctx.aux_losses[0]
            for a in ctx.aux_losses[1:]:
                aux = aux + a
            loss_list = [loss_list[0] + aux] + list(loss_list[1:])
    total = loss_list[0]
    for l in loss_list[1:]:
        total = total + l
    return ModelOutput(total, tuple(loss_list), video_loss, acc, token_loss,
                       frame_out, token_out)


def _pipeline_seq(cfg: Config):
    """(depth, block-config) group order + stage slot count for the
    pipelined body's stage-stacked parameter layout."""
    seq = [(i, c) for i in range(cfg.depth) for c in range(len(cfg.block_config))]
    assert len(seq) % cfg.pipeline_parallel == 0
    return seq, len(seq) // cfg.pipeline_parallel


def pipeline_params_stacked(cfg: Config, params) -> bool:
    """True when ``params`` carry the stage-stacked pipeline layout (no
    per-depth keys for stage-1's first block group)."""
    if cfg.pipeline_parallel <= 1:
        return False
    seq, g = _pipeline_seq(cfg)
    probe = f"{cfg.model_mode}/body/{_block_scope(*seq[g])}/"
    return not any(k.startswith(probe) for k in params)


def stack_pipeline_params(cfg: Config, params, axes=None):
    """Flat per-depth params -> the stage-stacked pipeline layout.

    Body block groups are cut into ``cfg.pipeline_parallel`` contiguous
    stages; each stage-0 group key keeps its name but its leaf becomes
    ``[P, ...]`` (stage s's slice = the corresponding group of stage s), and
    the other stages' per-depth keys disappear.  With ``axes`` metadata the
    new leaves gain a leading ``PIPE_STAGE`` axis name, which the sharding
    rules map to the pipeline mesh axis — params AND optimizer slots then
    live 1/P-sharded per device with no per-step gather (the residency the
    reference's model parallelism never had; our PP extension, SURVEY.md
    §2.12).  Returns ``params`` or ``(params, axes)`` matching the input.

    Values may be arrays OR pytrees of arrays (e.g. per-param optimizer slot
    dicts, whose structure is identical across depths) — each leaf is stacked
    stage-wise, which is what the flat->stacked checkpoint migration needs."""
    from ..config import PIPE_STAGE
    seq, g = _pipeline_seq(cfg)
    P = cfg.pipeline_parallel
    root = f"{cfg.model_mode}/body"
    all_keys = list(params.keys())
    out = dict(params)
    new_axes = None if axes is None else dict(axes)
    for j in range(g):
        i0, c0 = seq[j]
        for k in _block_param_keys(all_keys, root, i0, c0, include_shared=False):
            parts = []
            for s in range(P):
                i, c = seq[s * g + j]
                src = k.replace(f"/{_block_scope(i0, c0)}/",
                                f"/{_block_scope(i, c)}/")
                parts.append(params[src])
                if s > 0:
                    del out[src]
                    if new_axes is not None:
                        del new_axes[src]
            out[k] = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *parts)
            if new_axes is not None:
                new_axes[k] = (PIPE_STAGE,) + tuple(new_axes[k])
    # cross-depth 'shared' tensors: REPLICATED per stage (identical slices
    # under the stage axis).  Their grads are stage-summed and re-broadcast
    # (sync_shared_pipeline_grads), so the per-stage optimizer updates stay
    # bit-identical and the copies never diverge — exact cross-depth sharing
    # semantics with stage residency.
    for k in all_keys:
        if k.startswith(f"{root}/shared_"):
            out[k] = jax.tree_util.tree_map(
                lambda x: jnp.broadcast_to(x[None], (P,) + x.shape), out[k])
            if new_axes is not None:
                new_axes[k] = (PIPE_STAGE,) + tuple(new_axes[k])
    return out if axes is None else (out, new_axes)


def unstack_pipeline_params(cfg: Config, params, axes=None):
    """Inverse of :func:`stack_pipeline_params`: recover the flat per-depth
    layout (used by inference/decode, which runs the plain chain)."""
    seq, g = _pipeline_seq(cfg)
    P = cfg.pipeline_parallel
    root = f"{cfg.model_mode}/body"
    all_keys = list(params.keys())
    out = dict(params)
    new_axes = None if axes is None else dict(axes)
    for j in range(g):
        i0, c0 = seq[j]
        for k in _block_param_keys(all_keys, root, i0, c0, include_shared=False):
            v = out.pop(k)
            assert v.shape[0] == P, (k, v.shape, P)
            base = None if new_axes is None else tuple(new_axes.pop(k))[1:]
            for s in range(P):
                i, c = seq[s * g + j]
                dst = k.replace(f"/{_block_scope(i0, c0)}/",
                                f"/{_block_scope(i, c)}/")
                out[dst] = v[s]
                if new_axes is not None:
                    new_axes[dst] = base
    # shared tensors: replicated slices (kept bit-identical by the grad
    # sync) — slice 0 recovers the single cross-depth tensor
    for k in all_keys:
        if k.startswith(f"{root}/shared_") and k in out:
            out[k] = jax.tree_util.tree_map(lambda x: x[0], out[k])
            if new_axes is not None:
                new_axes[k] = tuple(new_axes[k])[1:]
    return out if axes is None else (out, new_axes)


def sync_shared_pipeline_grads(cfg: Config, grads, axes):
    """Sum each stage-replicated 'shared' tensor's gradient over the stage
    axis and re-broadcast it.

    Exact cross-depth sharing semantics: the sequential model's shared-weight
    gradient is the sum over ALL depth uses; with per-stage copies each slice
    only accumulates its own stage's uses, so the stage-sum restores the
    total and the broadcast hands every stage the same gradient — identical
    per-stage optimizer updates keep the replicas bit-synced."""
    from ..config import PIPE_STAGE
    root = f"{cfg.model_mode}/body/shared_"
    out = dict(grads)
    for k, g in grads.items():
        if k.startswith(root) and tuple(axes.get(k, ()))[:1] == (PIPE_STAGE,):
            out[k] = jnp.broadcast_to(jnp.sum(g, axis=0, keepdims=True),
                                      g.shape)
    return out


def init_params(cfg: Config, batch: typing.Dict[str, NT], seed: int = 0
                ) -> typing.Tuple[typing.Dict[str, jnp.ndarray],
                                  typing.Dict[str, typing.Tuple[str, ...]]]:
    """Run the model in collect mode; returns (params, name->axis-names).

    The collect pass is jitted: parameter names/axes are Python-level side
    effects gathered at trace time, values come back as one fused XLA
    computation (all the QR inits compile together)."""
    meta: typing.Dict[str, typing.Tuple[str, ...]] = {}

    def _collect():
        ctx = Ctx(cfg, params=None, seed=seed, train=False)
        build(ctx, batch)
        meta.update(ctx.axis_names)
        return ctx.collected

    params = jax.jit(_collect)()
    return dict(params), dict(meta)
