"""Functional parameter store with deterministic scoped naming.

The reference relies on TF variable scopes plus a global deterministic name
counter (``scoped``/``NAME_INDICES``, /root/reference/src/utils_core.py:16-67)
so that rebuilding the graph yields identical variable names — macro-batching
and the optimizer's name-based heuristics depend on it.  Here the same idea is
a pure-Python scope stack: every layer invocation pushes ``name{counter}`` and
parameters live in a flat ``dict[str, jnp.ndarray]`` pytree keyed by the scope
path.  Because JAX is functional there is no variable cache to invalidate: the
same ``Ctx`` machinery runs once for shape/param discovery (init) and then
inside ``jit`` for apply.

Weight sharing (the ``shared`` DSL flag, reference src/model/backend.py:43-94)
is reproduced by dropping the depth component from the scope path: the
reference's scope-parsing rotation assigns the k-th shared call within a block
in depth i>0 the variable created by the k-th call at depth 0, which is exactly
"same path modulo depth index".
"""
from __future__ import annotations

import hashlib
import typing

import jax
import jax.numpy as jnp

from .. import nd
from ..config import Config
from ..nd import NT

DEPTH_TOKEN = "@d"  # scope component that identifies the depth index


def _name_seed(name: str) -> int:
    return int.from_bytes(hashlib.blake2b(name.encode(), digest_size=4).digest(), "little")


class DecodeState:
    """KV-cache decode mode (infer/kv_cache.py): the model runs on ONE
    sequence position; ``pos`` is the absolute position of that row and
    ``caches`` maps attention-layer ids to (k, v) arrays of shape
    [batch, seq, heads, key].  Attention layers read/update their entry;
    position-dependent embeddings slice their row at ``pos``."""

    def __init__(self, pos, caches: typing.Dict[str, tuple], seq: int):
        self.pos = pos
        self.caches = caches
        self.seq = seq


class Ctx:
    """Carries config + parameters + scope state through model construction."""

    def __init__(self, cfg: Config, params: typing.Optional[dict] = None,
                 seed: int = 0, train: bool = True,
                 rng: typing.Optional[jax.Array] = None, mesh=None,
                 decode: typing.Optional[DecodeState] = None,
                 outer_mesh=None):
        self.cfg = cfg
        self.params = params  # None => init (collect) mode
        self.mesh = mesh  # device mesh for shard_map islands (ring attention)
        # the concrete mesh when building INSIDE a manual shard_map region
        # (pipeline stage bodies): ``mesh`` must stay None there — a
        # with_sharding_constraint over the concrete mesh cannot apply inside
        # the region — but eligibility checks (ring/fused-kernel/blocked-map)
        # and the nested ring-attention path still need the real axis sizes
        self.outer_mesh = outer_mesh
        self.decode = decode  # KV-cache incremental decode state
        self.collected: typing.Dict[str, jnp.ndarray] = {}
        self.axis_names: typing.Dict[str, typing.Tuple[str, ...]] = {}
        self.train = train
        self.seed = seed
        self.rng = rng  # per-step PRNG key for dropout etc.
        self._scope: typing.List[str] = []
        self._counters: typing.Dict[typing.Tuple[str, str], int] = {}
        self._rng_counter = 0
        self.attention_idx = 0
        # stash for contrastive loss (reference dataclass.py:29-31)
        self.text_input_embedding: typing.Optional[NT] = None
        # layer-collected auxiliary loss terms (routed-MoE load balance);
        # only propagated out of non-reversible bodies — see _body
        self.aux_losses: typing.List[jnp.ndarray] = []
        self.param_count = 0

    @property
    def effective_mesh(self):
        """The mesh for eligibility decisions (ring/fused-kernel/blocked-map)
        regardless of where the build is running: ``mesh`` at top level,
        ``outer_mesh`` inside a pipeline stage.  Consumers that APPLY
        constraints must keep using ``mesh`` (None inside manual regions)."""
        return self.mesh if self.mesh is not None else self.outer_mesh

    # -- scoping ------------------------------------------------------------
    def scope(self, name: str) -> "_Scope":
        return _Scope(self, name)

    def preset_scope(self, *parts: str) -> "_PresetScope":
        """Seed the scope stack of a per-block sub-Ctx (reversible chain /
        pipeline stage builds) with an already-resolved prefix, mirroring it
        onto the nd diagnostic stack so rank-mismatch errors raised inside
        the block name the FULL parameter path, not just the block suffix."""
        return _PresetScope(self, parts)

    def scoped(self, name: str, fn, *args, **kwargs):
        with self.scope(name):
            return fn(*args, **kwargs)

    def path(self, name: str = "") -> str:
        return "/".join(self._scope + ([name] if name else []))

    # -- parameters ---------------------------------------------------------
    def param(self, name: str, names: typing.Sequence[str],
              sizes: typing.Sequence[int], init_fn,
              shared: bool = False, dtype=None) -> NT:
        """Fetch-or-create a parameter as an :class:`NT`.

        ``init_fn(key, shape) -> f32 array``; storage dtype from config.
        ``shared=True`` removes the depth component from the key so all depth
        iterations address one tensor."""
        full = self.path(name)
        if shared:
            # "@d{i}_{c}" -> "shared_{c}": one tensor per block-config slot,
            # reused across all depth iterations (reference backend.py:43-94).
            parts = []
            for p in full.split("/"):
                if p.startswith(DEPTH_TOKEN):
                    parts.append("shared_" + p.rsplit("_", 1)[1])
                else:
                    parts.append(p)
            full = "/".join(parts)
        # device-resident params live in slice_dtype (MTF's per-device slice
        # copy, reference dataclass.py:253-255); storage_dtype is the
        # checkpoint master copy (train/checkpoint.py casts on save)
        store_dtype = dtype or self.cfg.slice_dtype
        if self.params is not None:
            if full not in self.params:
                raise KeyError(f"missing parameter {full}")
            arr = self.params[full]
            return NT(arr.astype(self.cfg.calculation_dtype), tuple(names))
        if full not in self.collected:
            key = jax.random.key(self.seed)
            key = jax.random.fold_in(key, _name_seed(full))
            arr = init_fn(key, tuple(int(s) for s in sizes)).astype(store_dtype)
            self.collected[full] = arr
            self.axis_names[full] = tuple(names)
            self.param_count += int(arr.size)
        return NT(self.collected[full].astype(self.cfg.calculation_dtype), tuple(names))

    # -- randomness ---------------------------------------------------------
    def next_rng(self) -> jax.Array:
        if self.rng is None:
            # init mode: deterministic placeholder
            self.rng = jax.random.key(self.seed + 1)
        self._rng_counter += 1
        return jax.random.fold_in(self.rng, self._rng_counter)

    def dropout(self, t: NT, rate: float) -> NT:
        if not self.train or rate <= 0.0:
            return t
        keep = 1.0 - rate
        mask = jax.random.bernoulli(self.next_rng(), keep, t.x.shape)
        return NT(jnp.where(mask, t.x / keep, 0).astype(t.dtype), t.names)


class _Scope:
    def __init__(self, ctx: Ctx, name: str):
        self.ctx = ctx
        self.name = name

    def __enter__(self):
        ctx = self.ctx
        key = ("/".join(ctx._scope), self.name)
        idx = ctx._counters.get(key, 0)
        ctx._counters[key] = idx + 1
        resolved = f"{self.name}{idx}" if idx else self.name
        ctx._scope.append(resolved)
        # mirror onto the nd-module diagnostic stack so rank-mismatch errors
        # (and analyzer findings) can name the enclosing parameter path
        nd.push_scope(resolved)
        return ctx

    def __exit__(self, *exc):
        self.ctx._scope.pop()
        nd.pop_scope()
        return False


class _PresetScope:
    def __init__(self, ctx: Ctx, parts: typing.Sequence[str]):
        self.ctx = ctx
        self.parts = tuple(parts)

    def __enter__(self):
        self.ctx._scope = list(self.parts)
        for p in self.parts:
            nd.push_scope(p)
        return self.ctx

    def __exit__(self, *exc):
        for _ in self.parts:
            nd.pop_scope()
        return False


class Args:
    """Layer-call carrier: (ctx, tensor, name_extras, is_last) — the JAX
    analogue of the reference's BlockArgs (dataclass.py:387-419).  Calling it
    rebinds tensor / extras, mirroring the reference API so layer code reads
    the same way."""

    __slots__ = ("ctx", "tensor", "name_extras", "is_last")

    def __init__(self, ctx: Ctx, tensor: typing.Optional[NT],
                 name_extras: typing.List[str], is_last: bool = False):
        self.ctx = ctx
        self.tensor = tensor
        self.name_extras = list(name_extras)
        self.is_last = is_last

    @property
    def cfg(self) -> Config:
        return self.ctx.cfg

    def __call__(self, *args):
        new = Args(self.ctx, self.tensor, self.name_extras[:], self.is_last)
        for a in args:
            if isinstance(a, NT):
                new.tensor = a
            elif isinstance(a, (list, tuple)):
                new.name_extras = list(a)
            elif isinstance(a, str):
                new.name_extras.append(a)
            elif isinstance(a, Ctx):
                new.ctx = a
            else:
                raise ValueError(f"unsupported Args argument {a!r}")
        return new

    def __iter__(self):
        return iter(self.name_extras)

    def __contains__(self, item):
        return item in self.name_extras

    def __len__(self):
        return len(self.name_extras)

    def __getitem__(self, idx):
        return self.name_extras[idx]
