"""The layer library behind the block DSL.

Covers every entry of the reference registry (/root/reference/src/model/
frontend.py:58-75): feed_forward, attention, cummean, cumsum, norm, rezero,
activation, convolution, dropout, group_linear, split_path, product-key
memories, reduced_half_linear, transpose_sequence_features,
bottleneck_group_linear, sum_heads — re-expressed over named jnp axes.
"""
from __future__ import annotations

import math
import typing

import jax
import jax.numpy as jnp

from .. import nd
from ..config import (HEADS, INTERMEDIATE, KEY, PKM_AXES, PKM_VALUES,
                      ROUTED_EXPERTS, SEQUENCE, anonymize_name)
from ..nd import NT
from ..ops.activations import ACTIVATIONS, activate
from .ctx import Args
from .embedding import embed, gather_embed
from .linear import (Dim, get_intermediate, linear, linear_shapes, normal_var,
                     orthogonal_var, scalar_var, wrapped_linear)

ATTENTION_DIM = typing.NamedTuple("AttentionDim", (("index", int), ("dim", str)))

# layer-local scratch axis: the routed-MoE dispatch flattens all non-group
# token axes into one row axis ("_rows", anonymized: never sharded)
nd.register_axis("rows")


# -- shape helpers ----------------------------------------------------------

def get_attention_dim(args: Args) -> ATTENTION_DIM:
    """Attention rotates over all non-feature, non-batch axes by a global
    counter — multi-axis attention for video (reference utils_mtf.py:418-422)."""
    cfg = args.cfg
    skip = set(cfg.feature_dims) | {INTERMEDIATE}
    dims = [n for n in args.tensor.names if n not in skip][1:]
    idx = args.ctx.attention_idx % len(dims)
    return ATTENTION_DIM(idx, dims[idx])


def is_masked(args: Args) -> bool:
    return get_attention_dim(args).index in args.cfg.masked_attention_dimensions


# -- simple layers ----------------------------------------------------------

def rezero(args: Args) -> NT:
    return args.tensor * scalar_var(args, 0.0, name="rezero_var")


def dropout(args: Args) -> NT:
    rate = 0.0
    for extra in args.name_extras:
        if extra.startswith("dropout_rate"):
            rate = float(extra[len("dropout_rate"):])
    return args.ctx.dropout(args.tensor, rate)


def norm(args: Args, feature_shape: typing.Optional[typing.List[Dim]] = None) -> NT:
    """Group/layer norm via named reductions (reference normalization.py:22-34).
    'group' keeps the head axis inside the normalized set; 'scale'/'shift' add
    learned affine parameters over the feature dims.

    HBM-lean formulation (docs/perf/README.md roofline: the norm family's
    backward dominates per-block traffic): both moments come from ONE pass
    over the input (var = E[x^2] - E[x]^2, f32 accumulators — more accurate
    than the previous bf16 two-pass), and centering folds into a per-position
    affine ``x*mul + add`` so no centered full-size temporary is ever
    materialized.  Measured on-chip at flagship width: 0.138 vs 0.257 GB per
    fwd+bwd norm call."""
    t = args.tensor
    if feature_shape is None:
        feature_shape = linear_shapes(args)[0]
    fnames = [n for n, _ in feature_shape]
    reduced = [n for n in fnames if not (n == HEADS and "group" in args)]
    cdtype = t.x.dtype
    xf = NT(t.x.astype(jnp.float32), t.names)
    m1 = nd.reduce_mean(xf, reduced=reduced)
    m2 = nd.reduce_mean(xf * xf, reduced=reduced)
    var = jnp.maximum(m2.x - jnp.square(m1.x), 0.0)
    mul = NT(jax.lax.rsqrt(var + 1e-5), m2.names)
    if "scale" in args:
        p = normal_var(args, feature_shape, mean=1.0, name="scale")
        mul = mul * NT(p.x.astype(jnp.float32), p.names)
    add = -m1 * mul
    if "shift" in args:
        p = normal_var(args, feature_shape, mean=0.0, name="shift")
        add = add + NT(p.x.astype(jnp.float32), p.names)
    out = xf * mul + add
    return NT(out.x.astype(cdtype), out.names).transpose_to(t.names)


# -- feed-forward family ----------------------------------------------------

def mixture_of_experts(args: Args) -> NT:
    """Dense soft-MoE: softmax gate over the expert axis contracted into a
    per-expert linear (reference basic.py:37-44)."""
    cfg = args.cfg
    old, new = linear_shapes(args)
    expert = (anonymize_name("experts") if "experts" in [n for n, _ in old + new]
              else "experts")
    gate = linear(args, old, [(expert, cfg.experts)])
    gate = gate - nd.stop_gradient(nd.reduce_max(gate, reduced=[expert]))
    gate = NT(jnp.exp(gate.x), gate.names)
    w = args.ctx.scoped("orthogonal_var", orthogonal_var, args,
                        list(old) + list(new) + [(expert, cfg.experts)], old)
    denom = NT(jnp.reciprocal(nd.reduce_sum(gate, reduced=[expert]).x),
               tuple(n for n in gate.names if n != expert))
    out_names = nd.dedup([n for n in args.tensor.names
                          if n not in {o for o, _ in old} - {f for f, _ in new}]
                         + [f for f, _ in new])
    return nd.einsum([denom, args.tensor, gate, w], out_names)


def activated_linear(args: Args, prefix: str) -> NT:
    args = args([a[len(prefix):] for a in args if a.startswith(prefix)])
    ff = mixture_of_experts if "mixture_of_experts" in args else wrapped_linear
    out = dropout(args(activate(args(ff(args)))))
    if "glu" in args or "glu_add" in args:
        out = out * NT(jax.nn.sigmoid(ff(args).x), out.names)
    if "glu_add" in args:
        out = out + activate(args(ff(args)))
    if "norm" in args:
        out = norm(args(out))
    return out


def activated_linear_in(args: Args) -> NT:
    return activated_linear(args, "in:")


def activated_linear_out(args: Args) -> NT:
    return activated_linear(args, "out:")


def feed_forward(args: Args) -> NT:
    return activated_linear_out(args(activated_linear_in(args)))


def group_linear(args: Args) -> NT:
    """Per-head square linear (reference basic.py:72-74)."""
    cfg = args.cfg
    fdims = [(n, cfg.dims[n]) for n in cfg.feature_dims]
    anon = [(HEADS, cfg.heads), (anonymize_name(KEY), cfg.features_per_head)]
    out = linear(args("group"), fdims, anon)
    return out.rename(anonymize_name(KEY), KEY).transpose_to(args.tensor.names)


def routed_mixture_of_experts(args: Args) -> NT:
    """Top-k routed MoE with expert parallelism — the all-to-all dispatch
    extension SURVEY.md §2.12 names (the reference only has the dense soft
    MoE, basic.py:37-44).

    GShard/Switch-style dense dispatch with BATCH as the routing group axis
    (GShard's [G, S, E, C] layout): per batch row, gate -> top-k expert
    choices -> capacity-bounded one-hot dispatch/combine tensors -> per-
    expert FFN.  Capacity is per (group, expert), so dispatch memory is
    linear in tokens, and the group axis stays data-sharded.  Experts shard
    over the DATA mesh axis (parallel/sharding.py ROUTED_EXPERTS rule) while
    features stay head-sharded on the model axis; the dispatch/combine
    einsums between token-sharded and expert-sharded layouts make GSPMD emit
    the token<->expert all-to-all over ICI — no hand-written collectives.

    DSL: ``routed_moe[-topk<k>][-capacity<f>][-in:<act>]``, e.g.
    ``routed_moe-topk2-capacity1.5-in:relu`` (activation defaults to relu).
    Dropped tokens (expert over capacity) pass through with a zero expert
    contribution (their residual path is the block skip).  Combine weights
    are normalized over the selected k, so with identical experts the layer
    reduces exactly to one FFN — the property the parity test checks.

    A Switch-style load-balance auxiliary loss (E * sum_e f_e*P_e per group,
    scaled by ``cfg.moe_balance_weight``) is collected via ``ctx.aux_losses``
    and added to the first loss term.  Under ``memory_reduction_strategy``
    "none" it is collected directly; under "checkpoint" it is threaded
    through ``jax.checkpoint`` as a real block output.  The reversible
    strategies (revnet/momentum) cannot carry it across their custom_vjp
    boundary, so config validation rejects that combination whenever
    ``moe_balance_weight > 0`` (config.py)."""
    from ..parallel.sharding import constraint
    cfg = args.cfg
    ctx = args.ctx
    t = args.tensor
    topk = 1
    cap_factor = 1.25
    for extra in args.name_extras:
        if extra.startswith("topk"):
            topk = int(extra[len("topk"):])
        elif extra.startswith("capacity"):
            cap_factor = float(extra[len("capacity"):])
    n_exp = cfg.experts
    topk = min(topk, n_exp)

    fdims = [(n, cfg.dims[n]) for n in cfg.feature_dims]
    fnames = [n for n, _ in fdims]
    inter = (INTERMEDIATE, cfg.intermediate_size)
    re_dim = (ROUTED_EXPERTS, n_exp)
    group_axis = t.names[0]  # batch: the GShard routing group

    # flatten the remaining non-feature axes into one row axis per group
    token_axes = [n for n in t.names if n not in fnames]
    assert token_axes[0] == group_axis
    xt = t.transpose_to(token_axes + fnames)
    lead = xt.x.shape[:len(token_axes)]
    n_groups = lead[0]
    rows = 1
    for s in lead[1:]:
        rows *= s
    feat_shape = xt.x.shape[len(token_axes):]
    x = NT(xt.x.reshape((n_groups, rows) + feat_shape),
           (group_axis, "_rows") + tuple(fnames))

    # gate (f32 for a stable softmax over experts)
    gate_w = normal_var(args, fdims + [re_dim], cfg.embedding_stddev,
                        name="router")
    logits = nd.einsum([x, gate_w], (group_axis, "_rows", ROUTED_EXPERTS)
                       ).astype(jnp.float32)
    probs = jax.nn.softmax(logits.x, axis=-1)  # [G, S, E]
    top_p, top_idx = jax.lax.top_k(probs, topk)  # [G, S, k]
    top_p = top_p / jnp.maximum(jnp.sum(top_p, -1, keepdims=True), 1e-9)

    capacity = max(1, int(rows * topk * cap_factor / n_exp))
    dispatch = jnp.zeros((n_groups, rows, n_exp, capacity), jnp.float32)
    combine = jnp.zeros((n_groups, rows, n_exp, capacity), jnp.float32)
    counts = jnp.zeros((n_groups, n_exp), jnp.int32)
    for j in range(topk):  # static unroll over the k slots
        onehot = jax.nn.one_hot(top_idx[..., j], n_exp, dtype=jnp.float32)
        pos = jnp.cumsum(onehot, axis=1) - onehot + counts[:, None, :]
        pos_tok = jnp.sum(pos * onehot, axis=-1).astype(jnp.int32)  # [G, S]
        keep = (pos_tok < capacity).astype(jnp.float32)
        slot = jax.nn.one_hot(jnp.minimum(pos_tok, capacity - 1), capacity,
                              dtype=jnp.float32)
        d = onehot[..., None] * slot[..., None, :] * keep[..., None, None]
        dispatch = dispatch + d
        combine = combine + d * top_p[..., j, None, None]
        counts = counts + jnp.sum(onehot, axis=1).astype(jnp.int32)

    if cfg.moe_balance_weight > 0:
        # Switch-style balance: E * sum_e (fraction dispatched to e) *
        # (mean router prob of e), averaged over groups; 1.0 at uniform
        frac = jnp.mean(dispatch.sum(-1), axis=1)        # [G, E]
        mean_p = jnp.mean(probs, axis=1)                 # [G, E]
        balance = n_exp * jnp.mean(jnp.sum(frac * mean_p, -1)) / topk
        ctx.aux_losses.append(
            jnp.float32(cfg.moe_balance_weight) * balance)

    cdtype = cfg.calculation_dtype
    disp_names = (group_axis, "_rows", ROUTED_EXPERTS, "_expert_capacity")
    disp = NT(dispatch.astype(cdtype), disp_names)
    comb = NT(combine.astype(cdtype), disp_names)

    # dispatch tokens to expert shards: the group axis becomes anonymous on
    # the expert side (each expert shard holds tokens from every group), so
    # GSPMD emits the all-to-all over the data axis
    e_names = (ROUTED_EXPERTS, anonymize_name(group_axis), "_expert_capacity")
    expert_in = nd.einsum([disp.rename(group_axis, anonymize_name(group_axis)),
                           x.rename(group_axis, anonymize_name(group_axis))],
                          e_names + tuple(fnames))
    if ctx.mesh is not None:
        expert_in = constraint(expert_in, ctx.mesh)

    w_in = args.ctx.scoped(
        "orthogonal_var", orthogonal_var, args,
        [re_dim] + fdims + [inter], fdims)
    w_out = args.ctx.scoped(
        "orthogonal_var", orthogonal_var, args,
        [re_dim, inter] + fdims, [inter])
    h = nd.einsum([expert_in, w_in], e_names + (INTERMEDIATE,))
    acts = [a[len("in:"):] for a in args.name_extras if a.startswith("in:")]
    h = activate(args(acts or ["relu"])(h))
    expert_out = nd.einsum([h, w_out], e_names + tuple(fnames))
    if ctx.mesh is not None:
        expert_out = constraint(expert_out, ctx.mesh)

    # combine back to token-sharded layout (second all-to-all)
    y = nd.einsum(
        [comb.rename(group_axis, anonymize_name(group_axis)), expert_out],
        (anonymize_name(group_axis), "_rows") + tuple(fnames))
    out = NT(y.x.reshape(lead + feat_shape), tuple(token_axes + fnames))
    return out.transpose_to(t.names)


def sum_heads(args: Args) -> NT:
    return nd.reduce_sum(args.tensor, reduced=[HEADS])


def transpose_sequence_features(args: Args) -> NT:
    """Token-mixing transpose: swap sequence and feature axes (reference
    basic.py:81-86; requires seq == features_per_head)."""
    cfg = args.cfg
    assert cfg.features_per_head == cfg.sequence_length, "seq must equal features_per_head"
    t = args.tensor
    swapped = tuple(KEY if n == SEQUENCE else SEQUENCE if n == KEY else n
                    for n in t.names)
    return NT(t.x, swapped).transpose_to(t.names)


def reduced_half_linear(args: Args) -> NT:
    """Head-summed input passed through a per-head linear back to feature
    shape (reference basic.py:89-90; the reference's trailing reshape is
    shape-inconsistent there, so we re-expand via a features linear)."""
    cfg = args.cfg
    reduced = nd.reduce_sum(args.tensor, reduced=[HEADS])
    fdims = [(n, cfg.dims[n]) for n in cfg.feature_dims]
    return linear(args(reduced), [(KEY, cfg.features_per_head)], fdims
                  ).transpose_to(args.tensor.names)


def product_key_memory(args: Args) -> NT:
    """PKM sparse memory: per-axis key assignment, stable softmax normalizer,
    top-1 per axis, gather from a f^2-entry value table (reference
    basic.py:93-115).  The reference does the normalizer in fp64; TPUs have no
    native f64 so we use f32 (documented divergence)."""
    cfg = args.cfg
    anon_key = anonymize_name(KEY)
    features = [(PKM_AXES, cfg.pkm_axes), (anon_key, cfg.features_per_head)]
    old, _ = linear_shapes(args)
    assignment = linear(args, old, [(HEADS, cfg.heads)] + features)
    assignment = norm(args(assignment), features)
    assignment = assignment.astype(jnp.float32)
    normalizer = nd.reduce_max(assignment, reduced=[anon_key])
    normalizer = nd.reduce_sum(normalizer, reduced=[PKM_AXES])
    assignment = assignment - nd.stop_gradient(normalizer)
    assignment = NT(jnp.exp(assignment.x), assignment.names)
    norm_sum = nd.reduce_sum(assignment, reduced=[anon_key])  # [..., pkm]
    ax = norm_sum.names.index(PKM_AXES)
    normalizer = NT(jnp.prod(norm_sum.x, axis=ax),
                    tuple(n for n in norm_sum.names if n != PKM_AXES))

    pk_ax = assignment.names.index(anon_key)
    val = jnp.max(assignment.x, axis=pk_ax)
    idx = jnp.argmax(assignment.x, axis=pk_ax)
    val_nt = NT(val, tuple(n for n in assignment.names if n != anon_key))
    idx_nt = NT(idx, val_nt.names)
    # combine per-axis indices into one flat value index: sum idx_i * f**i
    powers = (cfg.features_per_head ** jnp.arange(cfg.pkm_axes)).astype(jnp.int32)
    ax2 = idx_nt.names.index(PKM_AXES)
    flat_idx = jnp.tensordot(idx_nt.x.astype(jnp.int32),
                             powers, axes=([ax2], [0]))
    flat_idx_nt = NT(flat_idx, tuple(n for n in idx_nt.names if n != PKM_AXES))
    val_prod = NT(jnp.prod(val_nt.x, axis=ax2), flat_idx_nt.names)
    val_final = (val_prod / normalizer).astype(cfg.calculation_dtype)

    fdims = [(n, cfg.dims[n]) for n in cfg.feature_dims]
    out, _ = gather_embed(args(flat_idx_nt),
                          [(PKM_VALUES, cfg.product_key_value_vectors)] + fdims,
                          squeeze_dims=[HEADS])
    return out * val_final


def feed_forward_product_key_memory(args: Args) -> NT:
    return product_key_memory(args(activated_linear_in(args)))


def bottleneck_group_linear(args: Args) -> NT:
    """3-stage grouped MLP: dense bottleneck in, per-head widened mid, per-head
    out (reference basic.py:122-126)."""
    args = args(activated_linear_in(args))
    args.name_extras.extend(["group", "mid:group", "out:group"])
    args = args(activated_linear(args, "mid:"))
    return activated_linear_out(args)


# -- attention / spatial mixing --------------------------------------------

def _causal_mask(args: Args, dim: str, tmp: str, keep_ge: bool) -> NT:
    size = args.tensor.dim_size(dim)
    op = jnp.greater_equal if keep_ge else jnp.less
    return nd.compare_range(dim, size, tmp, size, op, args.cfg.calculation_dtype)


def _masked_map(args: Args) -> typing.Tuple[NT, typing.Union[NT, int]]:
    """Learned per-head position-pair bias map, optionally causal-masked
    (reference spatial.py:19-23)."""
    cfg = args.cfg
    dim = get_attention_dim(args).dim
    tmp = anonymize_name(dim)
    size = args.tensor.dim_size(dim)
    bias = embed(args, [(HEADS, cfg.heads), (dim, size), (tmp, size)])
    mask = _causal_mask(args, dim, tmp, keep_ge=True) if is_masked(args) else 1
    return bias, mask


def _ring_eligible(args: Args, dim: str) -> bool:
    """Sequence-parallel ring attention replaces the plain dot-product
    softmax path when the mesh has a sequence axis; the learned-bias-map
    variants keep the GSPMD path (their seq x seq parameters are row-sharded
    instead).  Inside a pipeline stage (ctx.mesh is None there) the real
    mesh arrives via ctx.outer_mesh and the ring nests (ops/ring.py)."""
    from ..parallel.mesh import SEQ_AXIS
    mesh = args.ctx.effective_mesh
    return (mesh is not None
            and args.ctx.params is not None
            and mesh.shape.get(SEQ_AXIS, 1) > 1
            and dim == SEQUENCE
            and "dot_product" in args
            # the ring kernel is rank-4 (batch, seq, heads, key); video
            # tensors with height/width axes keep the GSPMD path
            and set(args.tensor.names) == {args.tensor.names[0], dim,
                                           HEADS, KEY}
            and not any(f in args for f in ("biased_softmax",
                                            "biased_attention_map",
                                            "scale_attention_map")))


def _qkv(args: Args, base: typing.Optional[Args], dim: str
         ) -> typing.Tuple[typing.Optional[NT], typing.Optional[NT], NT]:
    """Q/K/V construction shared by the dense, ring, and KV-cached attention
    paths: key source selection (embedded/context/positional), query scaling,
    value source (shared_key_value/input_as_value/linear)."""
    cfg = args.cfg
    t = args.tensor
    dc = args.ctx.decode
    qry = key = None
    if "dot_product" in args:
        if "embedded" in args or "context" in args:
            key = activated_linear_out(base)
        if "embedded" in args or "positional" in args:
            from .embedding import positional_embed
            fdims = [(n, cfg.dims[n]) for n in cfg.feature_dims]
            pos = positional_embed(args, dim, t.dim_size(dim), fdims)
            key = pos if key is None else key + pos
        scale = (dc.seq if dc is not None else t.dim_size(dim)) ** -0.5
        qry = activated_linear_out(base) * scale
    if "dot_product" in args and "shared_key_value" in args:
        val = key
    elif "input_as_value" in args:
        val = t
    else:
        val = activated_linear_out(base)
    return qry, key, val


def _cached_attention(args: Args, qry: NT, key: NT, val: NT, dim: str) -> NT:
    """KV-cache decode (the fast path the reference lacks, SURVEY.md §7
    item 7): the layer sees ``R`` rows starting at absolute position
    ``ctx.decode.pos`` — R=1 for incremental decode, R=prompt length for the
    prefill pass that writes the whole prompt's K/V in one forward.

    Two families share this path:

    * ``dot_product``: the rows' K/V are written into the layer's cache and
      the dot-product runs against the cached prefix under a per-row causal
      mask.
    * learned maps (``biased_softmax`` / ``biased_attention_map`` /
      ``scale_attention_map`` — the flagship mixer,
      /root/reference/src/model/spatial.py:65-75, whose semantics are
      ``out[s] = sum_{t<=s} map[h,s,t] * v[t]``): only V is cached; the
      seq x seq map is built FULL-LENGTH (same scope walk and param shapes
      as training, like ``positional_embed``) and rows ``[pos, pos+R)`` are
      sliced out — O(seq * d) per decoded token instead of the rebuild
      sampler's O(seq * full forward).

    Greedy outputs match the rebuild-everything sampler because every
    output depends only on causally visible positions."""
    ctx = args.ctx
    cfg = args.cfg
    dc = ctx.decode
    t = args.tensor
    batch_axis = t.names[0]
    order = (batch_axis, dim, HEADS, KEY)
    tmp = anonymize_name(dim)
    cdtype = cfg.calculation_dtype
    has_dot = "dot_product" in args

    cache_id = f"attn{ctx.attention_idx}"
    v_cur = val.transpose_to(order).x.astype(cdtype)   # [b, R, h, dk]
    n_rows = v_cur.shape[1]
    # ``dc.pos`` is a scalar (one shared position — the serialized samplers
    # and the engine's prefill) or a [batch] vector (per-lane positions —
    # the continuous-batching decode step, serve/engine.py, where every
    # lane sits at its own depth in its own request); vector pos implies
    # R == 1 (one incremental row per lane per step)
    lanes = jnp.ndim(dc.pos) > 0
    if lanes and n_rows != 1:
        raise ValueError("per-lane decode positions require single-row "
                         f"steps (got {n_rows} rows)")
    if cache_id in dc.caches:
        cached = dc.caches[cache_id]
    else:  # template-building call: allocate zeroed full-length caches
        shape = (v_cur.shape[0], dc.seq) + v_cur.shape[2:]
        cached = tuple(jnp.zeros(shape, cdtype)
                       for _ in range(2 if has_dot else 1))
    if lanes:
        # per-lane scatter: lane b writes its row at absolute dc.pos[b]
        # (dynamic_update_slice cannot take per-batch starts)
        row_at = (jnp.arange(dc.seq)[None, :] == dc.pos[:, None])
        sel = row_at.reshape(row_at.shape + (1,) * (v_cur.ndim - 2))
    if has_dot:
        k_cache, v_cache = cached
        k_cur = key.transpose_to(order).x.astype(cdtype)
        k_cache = (jnp.where(sel, k_cur, k_cache) if lanes
                   else jax.lax.dynamic_update_slice_in_dim(k_cache, k_cur,
                                                            dc.pos, 1))
    else:
        v_cache, = cached
    v_cache = (jnp.where(sel, v_cur, v_cache) if lanes
               else jax.lax.dynamic_update_slice_in_dim(v_cache, v_cur,
                                                        dc.pos, 1))
    dc.caches[cache_id] = (k_cache, v_cache) if has_dot else (v_cache,)

    # per-row causal visibility: query row r (absolute position pos+r) sees
    # cached positions <= pos+r only; with per-lane pos the mask gains the
    # batch axis and every NT below broadcasts it by name
    if lanes:
        q_abs = dc.pos[:, None] + jnp.arange(n_rows)[None, :]
        vis = (jnp.arange(dc.seq)[None, None, :]
               <= q_abs[:, :, None]).astype(cdtype)
        vis_nt = NT(vis, (batch_axis, dim, tmp))
    else:
        q_abs = dc.pos + jnp.arange(n_rows)
        vis = (jnp.arange(dc.seq)[None, :] <= q_abs[:, None]).astype(cdtype)
        vis_nt = NT(vis, (dim, tmp))

    def map_rows(a: Args) -> NT:
        """Rows [pos, pos+R) of the learned per-head seq x seq map, causally
        zeroed when the axis is masked (dense-path ``bias * mask``)."""
        bias = embed(a, [(HEADS, cfg.heads), (dim, dc.seq), (tmp, dc.seq)])
        bx = bias.transpose_to((HEADS, dim, tmp)).x.astype(cdtype)
        if lanes:  # per-lane row gather: [h, B, R, seq]
            rows = NT(jnp.take(bx, q_abs, axis=1),
                      (HEADS, batch_axis, dim, tmp))
        else:
            rows = NT(jax.lax.dynamic_slice_in_dim(bx, dc.pos, n_rows, 1),
                      (HEADS, dim, tmp))
        return rows * vis_nt if is_masked(a) else rows

    logit: typing.Optional[NT] = None
    if has_dot:
        kn = NT(k_cache, (batch_axis, tmp, HEADS, KEY))
        logit = nd.einsum([qry.transpose_to(order), kn],
                          (batch_axis, dim, HEADS, tmp))
    if "biased_softmax" in args:
        b = map_rows(args)
        logit = b if logit is None else logit + b
    if logit is not None:
        logit = logit + NT((1 - vis) * jnp.asarray(-2e38, cdtype),
                           vis_nt.names)
        logit = logit - nd.stop_gradient(nd.reduce_max(logit, reduced=[tmp]))
        logit = NT(jnp.exp(logit.x), logit.names)
        logit = logit / nd.reduce_sum(logit, reduced=[tmp])
    if "biased_attention_map" in args:
        b = map_rows(args)
        logit = b if logit is None else logit + b
    if "scale_attention_map" in args:
        b = map_rows(args)
        logit = b if logit is None else logit * b
    out = nd.einsum([logit, NT(v_cache, (batch_axis, tmp, HEADS, KEY))],
                    t.names)
    return out


def _ring_attention(args: Args, qry: NT, key: NT, val: NT, dim: str) -> NT:
    """Dot-product attention over the sequence-parallel ring (ops/ring.py)."""
    from ..ops.ring import ring_attention
    from ..parallel.mesh import SEQ_AXIS
    from ..parallel.sharding import spec_for
    t = args.tensor
    order = (t.names[0], dim, HEADS, KEY)
    ctx = args.ctx
    mesh = ctx.effective_mesh
    spec = spec_for(order, mesh)
    out = ring_attention(qry.transpose_to(order).x, key.transpose_to(order).x,
                         val.transpose_to(order).x, mesh, SEQ_AXIS, spec,
                         causal=True)
    return NT(out, order).transpose_to(t.names)


def _blocked_map_rows(bias_x, val_x, depth: int):
    """Causal map-attention with the triangle decomposed into blocks:
    ``out[b,s,h,k] = sum_{t<=s} bias[h,s,t] * val[b,t,h,k]`` where the
    lower-left quadrant multiplies DENSE (no masked flops executed) and
    only the two shrinking diagonal quadrants recurse; leaves (<=256 rows
    or odd sizes) run the plain masked einsum.

    XLA executes a masked einsum as the FULL rectangle — the causal mask
    only zeroes operands — so at seq 2048 nearly half the seq^2 map FLOPs
    are wasted; depth 3 executes ~56% of the tile products and autodiff
    inherits the same saving in both backward contractions.  Measured
    on-chip at the 32ctx shape: ~25% faster per fwd+bwd call than the
    masked einsum (docs/perf/README.md round 5c); two hand-written pallas
    variants of the same skip LOSE to XLA here (ops/pallas_attn.py round
    2, ops/pallas_tri_attn.py round 5) — the win needs XLA's own schedule,
    just with the rectangle carved smaller.

    Partial sums accumulate in f32 (one cast at the top, strictly tighter
    than the single-einsum baseline's policy); plain jnp slicing/concat,
    so the decomposition composes with GSPMD sharding unchanged."""
    s = bias_x.shape[1]
    if depth <= 0 or s % 2 or s // 2 < 256:
        row = jax.lax.broadcasted_iota(jnp.int32, (s, s), 0)
        col = jax.lax.broadcasted_iota(jnp.int32, (s, s), 1)
        masked = bias_x * (row >= col).astype(bias_x.dtype)
        return jnp.einsum("hst,bthk->bshk", masked, val_x,
                          preferred_element_type=jnp.float32)
    half = s // 2
    top = _blocked_map_rows(bias_x[:, :half, :half], val_x[:, :half],
                            depth - 1)
    dense = jnp.einsum("hst,bthk->bshk", bias_x[:, half:, :half],
                       val_x[:, :half], preferred_element_type=jnp.float32)
    bot = dense + _blocked_map_rows(bias_x[:, half:, half:],
                                    val_x[:, half:], depth - 1)
    return jnp.concatenate([top, bot], axis=1)


def _blocked_map_eligible(args: Args, dim: str) -> bool:
    """The blocked decomposition replaces the pure learned-map path (no
    dot-product/softmax/scale combination) on the rank-4 text layout with
    a causally-masked sequence axis; any seq-sharding keeps the row-sharded
    einsum path (slicing the sequence would cross shard boundaries)."""
    from ..parallel.mesh import SEQ_AXIS
    ctx = args.ctx
    t = args.tensor
    mesh = ctx.effective_mesh
    return (args.cfg.blocked_causal_map > 0
            and is_masked(args)
            and ctx.decode is None
            and dim == SEQUENCE
            and t.names[1:] == (SEQUENCE, HEADS, KEY)
            and (mesh is None or mesh.shape.get(SEQ_AXIS, 1) == 1))


def attention(args: Args) -> NT:
    """Composable attention (reference spatial.py:42-81): optional QK^T
    softmax path, learned bias/scale attention maps, causal masking, and
    value source selection.  The product ``logit @ value`` and ``q @ k^T``
    are plain einsums -> MXU."""
    ctx = args.ctx
    cfg = args.cfg
    ctx.attention_idx += 1
    base = None
    if "dot_product" in args or "input_as_value" not in args:
        base = args(activated_linear_in(args))

    dim = get_attention_dim(args).dim
    qry, key, val_src = _qkv(args, base, dim)
    if ctx.decode is not None and dim == SEQUENCE and (
            "dot_product" in args
            or any(f in args for f in ("biased_softmax", "biased_attention_map",
                                       "scale_attention_map"))):
        return _cached_attention(args, qry, key, val_src, dim)
    if _ring_eligible(args, dim):
        return _ring_attention(args, qry, key, val_src, dim)
    tmp = anonymize_name(dim)
    t = args.tensor
    shape_names = t.names
    val = val_src.rename(dim, tmp)

    logit: typing.Optional[NT] = None

    def _biased(a: Args) -> NT:
        bias, mask = _masked_map(a)
        return bias * mask if isinstance(mask, NT) else bias

    if "dot_product" in args:
        old, _ = linear_shapes(args)
        contracted = [n for n, _ in old if n != HEADS]
        logit_names = tuple(n for n in shape_names if n not in contracted) + (tmp,)
        logit = nd.einsum([qry, key.rename(dim, tmp)], logit_names)
    if "biased_softmax" in args:
        b = _biased(args)
        logit = b if logit is None else logit + b
    if logit is not None:
        # the reference masks every softmax logit causally, regardless of
        # masked_attention_dimensions (spatial.py:68)
        logit = logit + _causal_mask(args, dim, tmp, keep_ge=False) * -2e38
        logit = logit - nd.stop_gradient(nd.reduce_max(logit, reduced=[tmp]))
        logit = NT(jnp.exp(logit.x), logit.names)
        logit = logit / nd.reduce_sum(logit, reduced=[tmp])
    if ("biased_attention_map" in args and logit is None
            and "scale_attention_map" not in args
            and _blocked_map_eligible(args, dim)):
        # pure learned-map path: same scope walk as _biased (the embed is
        # the next parameter either way), triangle applied by block
        # decomposition instead of a mask multiply
        bias, mask = _masked_map(args)
        order = (shape_names[0], dim, HEADS, KEY)
        out = _blocked_map_rows(bias.transpose_to((HEADS, dim, tmp)).x,
                                val_src.transpose_to(order).x,
                                args.cfg.blocked_causal_map)
        out = out.astype(args.cfg.calculation_dtype)
        return NT(out, order).transpose_to(shape_names)
    if "biased_attention_map" in args:
        b = _biased(args)
        logit = b if logit is None else logit + b
    if "scale_attention_map" in args:
        b = _biased(args)
        logit = b if logit is None else logit * b
    if logit is None:
        raise UserWarning(f"no spatial mixing in attention: {args.name_extras}")
    return nd.einsum([logit, val], shape_names)


def _cumsum_axis(args: Args) -> int:
    return args.tensor.names.index(get_attention_dim(args).dim)


def cumsum(args: Args) -> NT:
    return NT(jnp.cumsum(args.tensor.x, axis=_cumsum_axis(args)), args.tensor.names)


def cummean(args: Args) -> NT:
    dim = get_attention_dim(args).dim
    out = cumsum(args)
    denom = 1 + nd.arange(dim, args.tensor.dim_size(dim),
                          dtype=args.tensor.dtype)
    return out / denom


def convolution(args: Args) -> NT:
    """Causal 1D convolution over the rotating attention axis.  The
    reference's custom conv op is disabled in-tree ("Convolution is currently
    broken", reference convolution.py:129); this is a working TPU-native
    causal depthwise-style conv via lax.conv_general_dilated."""
    cfg = args.cfg
    dim = get_attention_dim(args).dim
    t = args.tensor
    ksize = cfg.convolution_size
    fdims = [(n, cfg.dims[n]) for n in cfg.feature_dims]
    w = orthogonal_var(args, [("_conv_kernel", ksize)] + fdims, name="conv_kernel")
    # causal depthwise conv: channels = all feature dims, window over `dim`
    feat_names = [n for n, _ in fdims if n in t.names]
    other = [n for n in t.names if n != dim and n not in feat_names]
    xt = t.transpose_to(other + [dim] + feat_names)
    lead = xt.x.shape[:len(other)]
    length = xt.x.shape[len(other)]
    chans = 1
    for s in xt.x.shape[len(other) + 1:]:
        chans *= s
    x2 = xt.x.reshape((-1, length, chans))  # N, W, C
    k = w.x.astype(t.dtype).reshape(ksize, 1, chans)  # W, I/group=1, C
    y = jax.lax.conv_general_dilated(
        x2, k, (1,), [(ksize - 1, 0)], feature_group_count=chans,
        dimension_numbers=("NWC", "WIO", "NWC"))
    y = y.reshape(lead + xt.x.shape[len(other):])
    return NT(y, tuple(other + [dim] + feat_names)).transpose_to(t.names)


# -- fused mixer block (pallas bytes lever) ---------------------------------

def _fused_norm_params(args: Args) -> typing.Tuple[NT, NT]:
    """The norm layer's scale/shift constructor pair, shared by both fused
    block replays so the two paths cannot diverge from the unfused norm()."""
    fs = linear_shapes(args)[0]
    scale = normal_var(args, fs, mean=1.0, name="scale")
    shift = normal_var(args, fs, mean=0.0, name="shift")
    return scale, shift


MIXER_FUSED_PATTERN = (
    "norm-shift-scale-features-group",
    "attention-biased_attention_map-absolute-input_as_value-shared",
    "norm-shift-scale-features-group",
    "activation-gelu",
    "attention-biased_attention_map-absolute-input_as_value-shared",
)


def fused_mixer_eligible(ctx, conf, x: NT) -> bool:
    """The fused kernel (ops/pallas_mixer.py) replaces exactly the mixer
    configs' block-2 chain, on an unsharded device, in apply mode, on the
    plain rank-4 text layout with the sequence axis causally masked."""
    cfg = ctx.cfg
    layer = conf.layer if isinstance(conf.layer, (list, tuple)) else None
    mesh = ctx.effective_mesh
    from ..ops import quant
    return (cfg.fused_mixer_block
            and not quant.pattern_quantized(cfg, MIXER_FUSED_PATTERN)
            and layer is not None and tuple(layer) == MIXER_FUSED_PATTERN
            and ctx.params is not None and ctx.decode is None
            and (mesh is None or mesh.size == 1)
            and x.names[1:] == (SEQUENCE, HEADS, KEY)
            and 0 in cfg.masked_attention_dimensions
            and x.dim_size(SEQUENCE) % 128 == 0
            and x.dim_size(KEY) % 128 == 0
            and jax.default_backend() in ("tpu", "axon", "cpu"))


def fused_mixer_block_part(conf, ctx, x: NT) -> NT:
    """Apply the 5-layer mixer block through the fused pallas kernel.

    The scope walk REPLAYS ``registry._get_block_part`` exactly — same
    ``ctx.scoped`` calls in the same order, same parameter constructors the
    unfused layers invoke — so parameter names, shapes, init and the
    attention-rotation counter are bit-identical to the unfused chain and
    checkpoints interchange freely between the two paths."""
    from ..ops.pallas_mixer import fused_mixer_block

    cfg = ctx.cfg
    collected: typing.List[NT] = []

    def attn_params(args: Args) -> NT:
        ctx.attention_idx += 1
        dim = get_attention_dim(args).dim
        tmp = anonymize_name(dim)
        size = args.tensor.dim_size(dim)
        return embed(args, [(HEADS, cfg.heads), (dim, size), (tmp, size)])

    specs = list(conf.layer)
    for idx, layer_spec in enumerate(specs, 1):
        name, *extras = layer_spec.split("-")
        args = Args(ctx, x, extras, idx == len(specs))
        if name == "norm":
            collected.append(ctx.scoped("norm_", _fused_norm_params, args))
        elif name == "attention":
            collected.append(ctx.scoped("attention_", attn_params, args))
        else:  # activation: consumes its scope slot, holds no parameters
            with ctx.scope("activation_"):
                pass

    (scale1, shift1), bias1, (scale2, shift2), bias2 = collected
    order = (x.names[0], SEQUENCE, HEADS, KEY)
    tmp_names = [n for n in bias1.names if n != HEADS]
    out_x = fused_mixer_block(
        x.transpose_to(order).x,
        bias1.transpose_to((HEADS,) + tuple(tmp_names)).x,
        bias2.transpose_to((HEADS,) + tuple(tmp_names)).x,
        scale1.transpose_to((HEADS, KEY)).x,
        shift1.transpose_to((HEADS, KEY)).x,
        scale2.transpose_to((HEADS, KEY)).x,
        shift2.transpose_to((HEADS, KEY)).x,
        jax.default_backend() not in ("tpu", "axon"),  # interpret on CPU
    )
    return NT(out_x, order).transpose_to(x.names)


# -- fused bottleneck-group-linear block (pallas bytes lever #2) ------------

GROUP_FUSED_PATTERN = (
    "norm-shift-scale-features-group",
    "bottleneck_group_linear-in:relu-mid:relu-mid:norm-mid:shift-mid:scale"
    "-mid:features",
)


def fused_group_eligible(ctx, conf, x: NT) -> bool:
    """The two-kernel pair (ops/pallas_group.py) replaces exactly the group
    configs' block-1 chain [group norm, bottleneck_group_linear] on an
    unsharded device, in apply mode, on the plain rank-4 text layout with
    lane-aligned widths (the block is per-position, so no mask/seq
    constraint applies — only tiling)."""
    cfg = ctx.cfg
    layer = conf.layer if isinstance(conf.layer, (list, tuple)) else None
    mid = cfg.features_per_head * cfg.group_linear_factor
    n_rows = (x.dim_size(x.names[0]) * x.dim_size(SEQUENCE)
              if SEQUENCE in x.names else 0)
    mesh = ctx.effective_mesh
    from ..ops import quant
    return (cfg.fused_group_linear
            # quantization wins over fusion: the pallas kernels run their
            # own unquantized matmuls, so a quant-declared block must take
            # the unfused chain where linear() applies the quantized path
            # (the graftcheck quant-dtype rule would flag the fallback)
            and not quant.pattern_quantized(cfg, GROUP_FUSED_PATTERN)
            and layer is not None and tuple(layer) == GROUP_FUSED_PATTERN
            and ctx.params is not None and ctx.decode is None
            and (mesh is None or mesh.size == 1)
            and x.names[1:] == (SEQUENCE, HEADS, KEY)
            and x.dim_size(KEY) % 128 == 0
            and mid % 128 == 0
            and cfg.intermediate_size % 128 == 0
            and n_rows % 128 == 0
            and jax.default_backend() in ("tpu", "axon", "cpu"))


def fused_group_block_part(conf, ctx, x: NT) -> NT:
    """Apply the [group norm, bottleneck_group_linear] block through the
    fused pallas kernel pair.

    The scope walk REPLAYS ``registry._get_block_part`` exactly — the same
    ``ctx.scoped`` calls in the same order with the same parameter
    constructors the unfused layers invoke (norm's normal_var pair, then
    inside the bottleneck scope: linear's scoped orthogonal_var for W1/W2,
    the mid-norm's normal_var pair, orthogonal_var for W3) — so parameter
    names, shapes and init are bit-identical to the unfused chain and
    checkpoints interchange freely between the two paths."""
    from ..ops.pallas_group import fused_group_linear_block

    cfg = ctx.cfg
    anon_key = anonymize_name(KEY)
    inter = cfg.intermediate_size
    mid = cfg.features_per_head * cfg.group_linear_factor
    in_dims = [(HEADS, cfg.heads), (KEY, cfg.features_per_head)]
    mid_dims = [(HEADS, cfg.heads), (anon_key, mid)]

    def bgl_params(args: Args):
        w1 = ctx.scoped("orthogonal_var", orthogonal_var, args,
                        in_dims + [(INTERMEDIATE, inter)], in_dims)
        old1 = [(INTERMEDIATE, inter)]
        w2 = ctx.scoped("orthogonal_var", orthogonal_var, args,
                        old1 + mid_dims, old1)
        s1 = normal_var(args, mid_dims, mean=1.0, name="scale")
        h1 = normal_var(args, mid_dims, mean=0.0, name="shift")
        w3 = ctx.scoped("orthogonal_var", orthogonal_var, args,
                        mid_dims + in_dims, mid_dims)
        return w1, w2, s1, h1, w3

    specs = list(conf.layer)
    norm_spec, bgl_spec = specs
    norm_args = Args(ctx, x, norm_spec.split("-")[1:], False)
    scale0, shift0 = ctx.scoped("norm_", _fused_norm_params, norm_args)
    bgl_args = Args(ctx, x, bgl_spec.split("-")[1:], True)
    w1, w2, s1, h1, w3 = ctx.scoped("bottleneck_group_linear_", bgl_params,
                                    bgl_args)

    order = (x.names[0], SEQUENCE, HEADS, KEY)
    out_x = fused_group_linear_block(
        x.transpose_to(order).x,
        w1.transpose_to((HEADS, KEY, INTERMEDIATE)).x,
        w2.transpose_to((INTERMEDIATE, HEADS, anon_key)).x,
        w3.transpose_to((HEADS, anon_key, KEY)).x,
        scale0.transpose_to((HEADS, KEY)).x,
        shift0.transpose_to((HEADS, KEY)).x,
        s1.transpose_to((HEADS, anon_key)).x,
        h1.transpose_to((HEADS, anon_key)).x,
        jax.default_backend() not in ("tpu", "axon"),  # interpret on CPU
    )
    return NT(out_x, order).transpose_to(x.names)
