"""Embeddings: absolute / axial / relative(-learned), plus gather lookup.

Reference: /root/reference/src/model/embedding.py.  The reference lowers
embedding lookup to a custom per-slice tf.gather with a hand-written
ScatterAdd gradient (embedding.py:39-125); in JAX the same thing is a plain
indexed gather whose VJP is XLA's scatter-add, so no custom op is needed.  The
sinusoidal relative embedding (embedding.py:128-172) is computed on-device at
trace time (stop-gradient) instead of host-side per slice.
"""
from __future__ import annotations

import math
import typing

import jax
import jax.numpy as jnp

from .. import nd
from ..config import INTERMEDIATE
from ..nd import NT
from .ctx import Args
from .linear import Dim, linear_shapes, normal_var, orthogonal_var


def _embed_var(args: Args, dims: typing.Sequence[Dim]) -> NT:
    if "orthogonal" in args:
        return orthogonal_var(args, dims, name="embed_orth")
    return normal_var(args, dims, args.cfg.embedding_stddev, name="embed_var")


def _multi_dim_flat_index(sizes: typing.Sequence[int], dtype) -> jnp.ndarray:
    """Flattened linear index over a multi-axis grid, as a dense grid array
    (reference embedding.py:16-22)."""
    total_shape = tuple(sizes)
    out = jnp.zeros(total_shape, dtype)
    stride = 1
    for idx, size in enumerate(sizes):
        r = jnp.arange(0, size * stride, stride, dtype=dtype)
        out = out + r.reshape([1] * idx + [size] + [1] * (len(sizes) - idx - 1))
        stride *= size
    return out


def relative_embedding(args: Args, position_dims: typing.Sequence[Dim],
                       feature_dims: typing.Sequence[Dim], out_dims: typing.Sequence[Dim]
                       ) -> NT:
    """Sinusoidal position embedding:
    ``sin(pos_index * exp(4*flat_feature_index/n_feat - log(n_pos/2pi))) * std``
    — geometric frequencies over the flattened feature grid.  Diverges from
    the reference (embedding.py:140-172), whose additive ``+ 4/n_feat`` form
    overflows float32 for n_feat > ~89; see the inline note below."""
    cfg = args.cfg
    dtype = cfg.calculation_dtype
    pos_sizes = [s for _, s in position_dims]
    feat_sizes = [s for _, s in feature_dims]
    position_count = 1
    for s in pos_sizes:
        position_count *= s
    feature_count = 1.0
    for s in feat_sizes:
        feature_count *= s

    positions = _multi_dim_flat_index(pos_sizes, jnp.float32)
    features = _multi_dim_flat_index(feat_sizes, jnp.float32)
    additive = 0.0
    if "cosine" in cfg.position_embedding:
        additive = jnp.mod(features, 2)
        features = (features - additive) / 2
        additive = additive * math.pi
        feature_count /= 2

    # Documented divergence: the reference computes
    # ``exp(flat_feature_index + 4/n_feat - log(n_pos/2pi))``
    # (embedding.py:166-168), which overflows float32 (-> inf -> sin=NaN) for
    # any feature count above ~89 — a latent upstream bug its shipped mixer
    # configs never hit (they use absolute bias-map embeddings).  The
    # geometric-frequency reading ``exp(4*i/n_feat - log(n_pos/2pi))`` gives
    # wavelengths from n_pos/2pi down to n_pos/(2pi*e^4), matches the
    # reference's magnitude for small feature counts, and stays finite.
    features = features * (4.0 / feature_count)
    features = features - math.log(position_count / 2.0 / math.pi)
    features = jnp.exp(features) + additive

    pos_nt = NT(positions, tuple(n for n, _ in position_dims))
    feat_nt = NT(features, tuple(n for n, _ in feature_dims))
    out_names = tuple(n for n, _ in out_dims)
    out = nd.einsum([pos_nt, feat_nt], nd.dedup(pos_nt.names + feat_nt.names))
    out = NT(jnp.sin(out.x) * cfg.embedding_stddev, out.names).transpose_to(out_names)
    return nd.stop_gradient(out.astype(dtype))


def _embed(args: Args, dims: typing.Sequence[Dim]) -> NT:
    cfg = args.cfg
    feature_in_tensor = dict(linear_shapes(args)[0]) if args.tensor is not None else {}
    feat_names = set(feature_in_tensor) | set(cfg.feature_dims) | {INTERMEDIATE}
    position_dims = [d for d in dims if d[0] not in feat_names]
    feature_dims = [d for d in dims if d[0] in feat_names]

    if "absolute" in args:
        return _embed_var(args, dims)
    if "axial" in args:
        splits = 2
        for a in args:
            if a.isdigit():
                splits = int(a)
                break
        tmp_dims: typing.List[Dim] = []
        variables: typing.List[NT] = []

        def _new_part(size: int):
            d = (f"_axial{len(tmp_dims)}", size)
            tmp_dims.append(d)
            variables.append(_embed_var(args, [d] + feature_dims))

        for _, size in position_dims:
            base = int(size ** (1 / splits))
            while size % base != 0:
                base -= 1
            _new_part(size // base ** (splits - 1))
            for _ in range(1, splits):
                _new_part(base)
        prod = nd.einsum(variables, [n for n, _ in tmp_dims] + [n for n, _ in feature_dims])
        tgt_names = tuple(n for n, _ in dims)
        flat = prod.x.reshape([s for _, s in position_dims] + [s for _, s in feature_dims])
        out = NT(flat, tuple(n for n, _ in position_dims + feature_dims))
        return out.transpose_to(tgt_names)
    if "relative" in args:
        out = relative_embedding(args, position_dims, feature_dims, dims)
        if "learned" in args:
            out = out * _embed_var(args, feature_dims)
        return out
    raise ValueError(f"unsupported embedding kind {args.name_extras}: "
                     "use relative(-learned) / absolute / axial")


def embed(args: Args, dims: typing.Sequence[Dim]) -> NT:
    return args.ctx.scoped("embed", _embed, args, dims)


def positional_embed(args: Args, dim: str, size: int,
                     fdims: typing.Sequence[Dim]) -> NT:
    """Position table over ``dim`` with KV-cache decode handling: under
    ``ctx.decode`` the table is built FULL-LENGTH (same scope walk and
    shape as training, so checkpointed weights resolve) and the current
    rows — width ``size`` at absolute position ``decode.pos`` — are sliced
    out.  Shared by the body's initial position embedding and attention's
    positional keys so the slicing invariant lives in one place."""
    from ..config import SEQUENCE
    dc = args.ctx.decode
    sliced = dc is not None and dim == SEQUENCE
    full = dc.seq if sliced else size
    out = embed(args, [(dim, full)] + list(fdims))
    if sliced:
        ax = out.names.index(dim)
        if jnp.ndim(dc.pos):
            # per-lane positions (continuous batching, serve/engine.py):
            # lane b reads its own rows [pos[b], pos[b]+size) — jnp.take
            # clips out-of-range rows, matching dynamic_slice's clamping.
            # The gathered table gains the caller's batch axis, which the
            # NT name-broadcast aligns with the activations downstream.
            rows = dc.pos[:, None] + jnp.arange(size)
            lane = args.tensor.names[0]
            out = NT(jnp.take(out.x, rows, axis=ax),
                     out.names[:ax] + (lane,) + out.names[ax:])
        else:
            out = NT(jax.lax.dynamic_slice_in_dim(out.x, dc.pos, size, ax),
                     out.names)
    return out


def gather(args: Args, table: NT, squeeze_dims: typing.Sequence[str] = ()) -> NT:
    """Embedding lookup: ids (int NT) index axis 0 of ``table``.

    ``squeeze_dims`` are axes shared between ids and table that must be
    looked up pointwise (the PKM per-head case, reference embedding.py:91-125
    where mesh-splitting makes the head axis per-slice size 1)."""
    ids = args.tensor
    squeeze = [n for n in squeeze_dims if n in ids.names and n in table.names]
    if not squeeze:
        out = table.x[ids.x.astype(jnp.int32)]
        return NT(out.astype(args.cfg.calculation_dtype),
                  ids.names + table.names[1:])
    if len(squeeze) != 1:
        raise NotImplementedError("only one shared gather axis supported")
    (ax,) = squeeze
    # table [V, ax, ...rest]; ids [..., ax] -> out [..., ax, ...rest]
    t = table.transpose_to((table.names[0], ax) + tuple(
        n for n in table.names[1:] if n != ax))
    i = ids.transpose_to(tuple(n for n in ids.names if n != ax) + (ax,))
    gathered = jax.vmap(lambda tab, idx: tab[idx], in_axes=(1, -1), out_axes=-1)(
        t.x, i.x.astype(jnp.int32))
    # gathered: [*ids_without_ax, *rest, ax] -> reorder
    names = tuple(n for n in i.names[:-1]) + t.names[2:] + (ax,)
    out = NT(gathered.astype(args.cfg.calculation_dtype), names)
    return out.transpose_to(tuple(n for n in i.names[:-1]) + (ax,) + t.names[2:])


def gather_embed(args: Args, dims: typing.Sequence[Dim],
                 squeeze_dims: typing.Sequence[str] = ()) -> NT:
    table = args.ctx.scoped("gather", embed, args, dims)
    out = gather(args, table, squeeze_dims)
    return out, table
