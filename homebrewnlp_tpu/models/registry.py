"""Layer-DSL registry + block assembly.

Layer spec strings are ``"name-extra1-extra2"`` (reference
src/model/frontend.py:21-36); ``split_path`` builds add/multiply parallel
branches from ``;``/``,``-separated sub-configs (frontend.py:39-55).
"""
from __future__ import annotations

import typing

from ..config import BlockConfig
from ..nd import NT
from ..ops.activations import activate
from .ctx import Args, Ctx
from . import layers


def _get_block_part(block_part_config: BlockConfig, ctx: Ctx, block_input: NT) -> NT:
    if layers.fused_mixer_eligible(ctx, block_part_config, block_input):
        # the mixer block-2 chain as ONE pallas fwd kernel + one full-vjp
        # bwd kernel (ops/pallas_mixer.py) — same parameters, same scope
        # walk, a fraction of the HBM traffic
        out = layers.fused_mixer_block_part(block_part_config, ctx,
                                            block_input)
    elif layers.fused_group_eligible(ctx, block_part_config, block_input):
        # the [group norm, bottleneck_group_linear] chain as two pallas
        # fwd+bwd kernel pairs split at the bottleneck activation
        # (ops/pallas_group.py) — same parameters, same scope walk, a
        # fraction of the HBM traffic
        out = layers.fused_group_block_part(block_part_config, ctx,
                                            block_input)
    else:
        out = block_input
        for idx, layer in enumerate(block_part_config.layer, 1):
            name, *extras = layer.split("-")
            if name not in LAYER_FUNCTIONS:
                raise ValueError(f"unknown layer {name!r} in spec {layer!r}; "
                                 f"known layers: {sorted(LAYER_FUNCTIONS)}")
            args = Args(ctx, out, extras, idx == len(block_part_config.layer))
            out = ctx.scoped(name + "_", LAYER_FUNCTIONS[name], args)
    if block_part_config.skip and block_part_config.memory_reduction_strategy in ("none", "checkpoint"):
        out = out + block_input
    return out


def block_part_fn(ctx: Ctx, block_part_config: BlockConfig, block_input: NT,
                  name_prefix: str = "block") -> NT:
    return ctx.scoped(f"{name_prefix}_", _get_block_part, block_part_config, ctx,
                      block_input)


def split_path(args: Args) -> NT:
    base, *branch_confs = "-".join(args.name_extras).split(";")
    base = base.split("-")
    if "add" in base:
        out: typing.Union[NT, int] = 0
        combine = lambda a, b: b if isinstance(a, int) else a + b
    elif "multiply" in base:
        out = 1
        combine = lambda a, b: b if isinstance(a, int) else a * b
    else:
        raise ValueError(f"split_path needs add/multiply base, got {base}")
    for conf in branch_confs:
        branch = _get_block_part(
            BlockConfig(layer=conf.split(","), skip=False,
                        memory_reduction_strategy=""),
            args.ctx, args.tensor)
        out = combine(out, branch)
    return out


LAYER_FUNCTIONS: typing.Dict[str, typing.Callable[[Args], NT]] = {
    "feed_forward": layers.feed_forward,
    "attention": layers.attention,
    "cummean": layers.cummean,
    "cumsum": layers.cumsum,
    "norm": layers.norm,
    "rezero": layers.rezero,
    "activation": activate,
    "convolution": layers.convolution,
    "dropout": layers.dropout,
    "group_linear": layers.group_linear,
    "split_path": split_path,
    "feed_forward_product_key_memory": layers.feed_forward_product_key_memory,
    "product_key_memory": layers.product_key_memory,
    "reduced_half_linear": layers.reduced_half_linear,
    "transpose_sequence_features": layers.transpose_sequence_features,
    "bottleneck_group_linear": layers.bottleneck_group_linear,
    "sum_heads": layers.sum_heads,
    # extension: top-k routed MoE with expert-parallel all-to-all dispatch
    # (SURVEY.md §2.12 row EP; the reference only has the dense soft MoE)
    "routed_moe": layers.routed_mixture_of_experts,
}
