"""Linear primitives + the DSL's dimension-selection rules.

Reproduces the semantics of ``linear_shapes``/``get_intermediate``
(/root/reference/src/utils_mtf.py:376-391) and ``linear``/``orthogonal_var``/
``normal_var`` (/root/reference/src/model/backend.py:97-118) over named jnp
axes.  A "Dim" here is a (name, size) pair; tensors carry names and sizes
directly, so no mtf Shape objects are needed.
"""
from __future__ import annotations

import typing

from .. import nd
from ..config import HEADS, INTERMEDIATE, KEY, anonymize_name
from ..nd import NT
from ..ops import quant
from ..ops.init import constant_init, default_fan_in, normal_init, orthogonal_init
from .ctx import Args

Dim = typing.Tuple[str, int]


def get_intermediate(args: Args) -> typing.List[Dim]:
    """Intermediate dims for a linear: plain -> [intermediate]; group -> a
    per-head widened key axis (reference utils_mtf.py:376-380)."""
    cfg = args.cfg
    if "group" not in args:
        return [(INTERMEDIATE, cfg.intermediate_size)]
    return [(HEADS, cfg.heads),
            (anonymize_name(KEY), cfg.features_per_head * cfg.group_linear_factor)]


def linear_shapes(args: Args) -> typing.Tuple[typing.List[Dim], typing.List[Dim]]:
    """(old, new) dim lists for a DSL linear (reference utils_mtf.py:383-391).

    old = dims shared between the tensor and the feature set (contracted);
    new = remaining feature dims (produced); in group mode the head dim stays
    on both sides (per-head block-diagonal linear)."""
    cfg = args.cfg
    t = args.tensor
    features: typing.List[Dim] = list(get_intermediate(args))
    for name in cfg.feature_dims:
        if name not in [f[0] for f in features]:
            features.append((name, cfg.dims[name]))
    if "group" in args and INTERMEDIATE in t.names:
        features = [f for f in features if f[0] != KEY]
        features.append((INTERMEDIATE, cfg.intermediate_size))
    fnames = [f[0] for f in features]
    # crossection ordered by (tensor names ++ features)
    old = [(n, t.dim_size(n)) for n in t.names if n in fnames]
    old_names = [n for n, _ in old]
    keep = {HEADS} if ("group" in args and HEADS in old_names) else set()
    new = [f for f in features if f[0] not in (set(old_names) - keep)]
    return old, new


def orthogonal_var(args: Args, dims: typing.Sequence[Dim],
                   fan_in: typing.Optional[typing.Sequence[Dim]] = None,
                   name: str = "orthogonal_var") -> NT:
    cfg = args.cfg
    names = nd.dedup([d[0] for d in dims])
    size_of = dict(dims)
    sizes = [size_of[n] for n in names]
    if fan_in is None:
        fan_names = default_fan_in(names, cfg.feature_dims)
        fan_sizes = [size_of[n] for n in fan_names]
    else:
        fan_sizes = [s for _, s in fan_in]
    scale = (cfg.depth ** -0.5) if (cfg.scale_by_depth and args.is_last) else 1.0
    init = orthogonal_init(sizes, fan_sizes, scale)
    return args.ctx.param(name, names, sizes, init, shared="shared" in args)


def normal_var(args: Args, dims: typing.Sequence[Dim], stddev: float = 0.02,
               mean: float = 0.0, name: str = "normal_var") -> NT:
    names = nd.dedup([d[0] for d in dims])
    size_of = dict(dims)
    sizes = [size_of[n] for n in names]
    return args.ctx.param(name, names, sizes, normal_init(stddev, mean),
                          shared="shared" in args)


def scalar_var(args: Args, value: float = 0.0, name: str = "rezero_var") -> NT:
    return args.ctx.param(name, (), (), constant_init(value), shared="shared" in args)


def linear(args: Args, old: typing.Sequence[Dim], new: typing.Sequence[Dim]) -> NT:
    """y = einsum(x, W[old+new]) contracting ``old`` (reference backend.py:108-110).

    When the enclosing layer scope falls inside ``cfg.quant_blocks`` the
    contraction runs the W8A8 quantized path (ops/quant.py: dynamic
    in-graph scales, f32-accumulated int8/fp8 dot, high-precision
    backward); otherwise — and always when the knob is unset — this is the
    exact pre-quant ``nd.einsum`` graph."""
    cfg = args.cfg
    w = args.ctx.scoped("orthogonal_var", orthogonal_var, args, list(old) + list(new), old)
    out_names = nd.dedup([n for n in args.tensor.names if n not in
                          {o[0] for o in old} - {f[0] for f in new}]
                         + [f[0] for f in new])
    if (quant.eligible(cfg, args.tensor)
            and quant.scope_matches(cfg.quant_blocks, args.ctx.path())):
        return quant.quant_einsum(args.tensor, w, out_names, cfg.quant_dtype)
    return nd.einsum([args.tensor, w], out_names)


def linear_to_features(args: Args, old: typing.Optional[typing.Sequence[Dim]] = None) -> NT:
    cfg = args.cfg
    new = [(n, cfg.dims[n]) for n in cfg.feature_dims]
    if old is None:
        old, _ = linear_shapes(args)
    return linear(args, old, new)


def linear_from_features(args: Args, new: typing.Optional[typing.Sequence[Dim]] = None) -> NT:
    cfg = args.cfg
    old = [(n, cfg.dims[n]) for n in cfg.feature_dims]
    return linear(args, old, new)


def wrapped_linear(args: Args) -> NT:
    old, new = linear_shapes(args)
    return linear(args, old, new)
