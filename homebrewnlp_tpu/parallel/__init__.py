"""Parallelism layer: device mesh + named-axis sharding rules.

The reference's entire parallelism config is two integers — ``tpu_size`` and
``heads`` — synthesized into ``mesh_shape="b:N,h:H"`` / ``layout="batch:b,
heads:h"`` and materialized by Mesh-TensorFlow's SimdMeshImpl
(/root/reference/src/dataclass.py:247-252, src/main.py:144-147).  Here the
same two integers build a `jax.sharding.Mesh` and the layout becomes a
logical-axis -> mesh-axis rule table; GSPMD inserts the collectives the MTF
lowering used to emit.  Extensions the reference lacks: a sequence-parallel
axis (ring attention).
"""
from .mesh import make_mesh  # noqa: F401
from .sharding import (constraint, nt_spec, param_shardings, spec_for,  # noqa: F401
                       tree_shardings)
