"""Logical-axis -> mesh-axis sharding rules.

The reference's ``layout="batch:b,heads:h"`` (dataclass.py:249-252) becomes a
rule table over the named axes that :mod:`homebrewnlp_tpu.nd` tensors and the
parameter metadata already carry.  Anonymized axes (leading ``_``) are
replicated — the exact JAX meaning of the reference's anonymize protocol
(utils_mtf.py:207-232): a ``_``-named twin of an axis is the all-gathered
copy.
"""
from __future__ import annotations

import typing

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..config import (ANON_PREFIX, BATCH, EXPERTS, HEADS, PIPE_STAGE,
                      ROUTED_EXPERTS, SEQUENCE)
from ..nd import NT
from .mesh import DATA_AXIS, MODEL_AXIS, PIPE_AXIS, SEQ_AXIS

# logical axis -> mesh axis.  Everything else is replicated — the reference
# layout splits only batch and heads (SURVEY.md §2.12); the experts mappings
# are our expert-parallel extension (the reference's MoE expert axis is never
# laid out, §2.12 row EP).  Routed (top-k) experts shard over the DATA axis:
# tokens live data-sharded, expert shards own disjoint experts, and the
# dispatch/combine einsums make GSPMD emit the token<->expert all-to-all
# across that axis while features stay head-sharded on the model axis.
RULES: typing.Dict[str, str] = {
    BATCH: DATA_AXIS,
    HEADS: MODEL_AXIS,
    SEQUENCE: SEQ_AXIS,
    EXPERTS: MODEL_AXIS,
    ROUTED_EXPERTS: DATA_AXIS,
    PIPE_STAGE: PIPE_AXIS,
}


def spec_for(names: typing.Sequence[str], mesh: Mesh,
             rules: typing.Optional[typing.Dict[str, str]] = None
             ) -> PartitionSpec:
    """PartitionSpec for a tuple of logical axis names.  Mesh axes of size 1
    are omitted (XLA treats them as replicated anyway, and omitting keeps
    specs valid on smaller meshes).  A mesh axis is used at most once per
    spec, first logical axis wins — e.g. an MoE weight carrying both heads
    and experts shards heads over the model axis and replicates experts."""
    rules = RULES if rules is None else rules
    parts: typing.List[typing.Optional[str]] = []
    used: typing.Set[str] = set()
    for n in names:
        mesh_axis = None if n.startswith(ANON_PREFIX) else rules.get(n)
        if (mesh_axis is not None and mesh_axis not in used
                and mesh.shape.get(mesh_axis, 1) > 1):
            parts.append(mesh_axis)
            used.add(mesh_axis)
        else:
            parts.append(None)
    while parts and parts[-1] is None:
        parts.pop()
    return PartitionSpec(*parts)


def nt_spec(t: NT, mesh: Mesh) -> PartitionSpec:
    return spec_for(t.names, mesh)


def constraint(t: NT, mesh: Mesh) -> NT:
    """Apply a sharding constraint to an NT inside jit (the replacement for
    the reference's anonymize/unanonymize resharding reshapes)."""
    sharding = NamedSharding(mesh, nt_spec(t, mesh))
    return NT(jax.lax.with_sharding_constraint(t.x, sharding), t.names)


def param_shardings(axes: typing.Dict[str, typing.Tuple[str, ...]], mesh: Mesh
                    ) -> typing.Dict[str, NamedSharding]:
    """NamedShardings for a flat param dict from its axis-name metadata.
    Head-sharded parameters land split over the model axis; everything else
    is replicated — mirroring MTF's variable placement under the reference
    layout."""
    return {name: NamedSharding(mesh, spec_for(n, mesh))
            for name, n in axes.items()}


def tree_shardings(axes_tree, mesh: Mesh):
    """Shardings for an arbitrary pytree of axis-name tuples (used for
    optimizer slot states)."""
    return jax.tree_util.tree_map(
        lambda names: NamedSharding(mesh, spec_for(names, mesh)),
        axes_tree, is_leaf=lambda x: isinstance(x, tuple))
