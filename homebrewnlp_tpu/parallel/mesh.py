"""Device-mesh construction from the config's parallelism knobs.

Axis order is (data, sequence, model): model innermost so tensor-parallel
collectives ride the fastest ICI links, data outermost so gradient all-reduce
tolerates DCN hops on multi-host — the same intent as the reference's
``mesh_shape="b:N,h:H"`` ordering (dataclass.py:247-252) where the head axis
maps to the minor mesh dimension.
"""
from __future__ import annotations

import typing

import jax
import numpy as np
from jax.sharding import Mesh

from ..config import Config

DATA_AXIS = "data"
MODEL_AXIS = "model"
SEQ_AXIS = "sequence_parallel"
PIPE_AXIS = "pipeline"

#: every mesh axis a PartitionSpec in this codebase may legally name — the
#: ground truth for graftcheck's sharding-spec validation
MESH_AXES = (DATA_AXIS, SEQ_AXIS, PIPE_AXIS, MODEL_AXIS)


def axis_sizes(cfg: Config, n_devices: int) -> typing.Dict[str, int]:
    """Resolve mesh axis sizes for ``n_devices``.  ``heads`` bounds the model
    axis; remaining devices fold into data parallelism (reference behavior:
    b = tpu_size / heads).  The pipeline axis (GPipe stages, ops/pipeline.py)
    is exactly ``cfg.pipeline_parallel``."""
    model = cfg.mesh_model
    seq = cfg.sequence_parallel
    pipe = cfg.pipeline_parallel
    denom = model * seq * pipe
    if n_devices % denom:
        # shrink the model axis to the largest divisor that fits
        model = 1
        for cand in range(min(cfg.mesh_model, n_devices), 0, -1):
            # the model axis must also divide the head count or head-sharded
            # parameters cannot be placed on the mesh
            if n_devices % (cand * seq * pipe) == 0 and cfg.heads % cand == 0:
                model = cand
                break
        denom = model * seq * pipe
        if n_devices % denom:
            raise ValueError(
                f"cannot factor {n_devices} devices into seq={seq} pipe={pipe}")
        print(f"WARNING: model axis shrunk from {cfg.mesh_model} to {model} "
              f"to factor {n_devices} devices (seq={seq}, pipe={pipe})")
    return {DATA_AXIS: n_devices // denom, SEQ_AXIS: seq, PIPE_AXIS: pipe,
            MODEL_AXIS: model}


def make_mesh(cfg: Config,
              devices: typing.Optional[typing.Sequence[jax.Device]] = None
              ) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    sizes = axis_sizes(cfg, len(devices))
    batch = cfg.train_batch_size
    if batch % sizes[DATA_AXIS]:
        # the data axis cannot exceed what the batch can shard over; drop to
        # the largest batch divisor and leave surplus devices out of the mesh
        data = max(d for d in range(1, sizes[DATA_AXIS] + 1)
                   if batch % d == 0)
        print(f"WARNING: data axis shrunk from {sizes[DATA_AXIS]} to {data} "
              f"(train_batch_size={batch}); "
              f"{(sizes[DATA_AXIS] - data) * sizes[SEQ_AXIS] * sizes[PIPE_AXIS] * sizes[MODEL_AXIS]}"
              " device(s) left unused")
        sizes[DATA_AXIS] = data
    names = (DATA_AXIS, SEQ_AXIS, PIPE_AXIS, MODEL_AXIS)
    n_used = 1
    for n in names:
        n_used *= sizes[n]
    grid = np.asarray(devices[:n_used]).reshape([sizes[n] for n in names])
    return Mesh(grid, names)
