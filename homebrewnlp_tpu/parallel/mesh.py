"""Device-mesh construction from the config's parallelism knobs.

Axis order is (data, sequence, model): model innermost so tensor-parallel
collectives ride the fastest ICI links, data outermost so gradient all-reduce
tolerates DCN hops on multi-host — the same intent as the reference's
``mesh_shape="b:N,h:H"`` ordering (dataclass.py:247-252) where the head axis
maps to the minor mesh dimension.
"""
from __future__ import annotations

import typing

import jax
import numpy as np
from jax.sharding import Mesh

from ..config import Config

DATA_AXIS = "data"
MODEL_AXIS = "model"
SEQ_AXIS = "sequence_parallel"
PIPE_AXIS = "pipeline"

#: every mesh axis a PartitionSpec in this codebase may legally name — the
#: ground truth for graftcheck's sharding-spec validation
MESH_AXES = (DATA_AXIS, SEQ_AXIS, PIPE_AXIS, MODEL_AXIS)


def axis_sizes(cfg: Config, n_devices: int,
               quiet: bool = False) -> typing.Dict[str, int]:
    """Resolve mesh axis sizes for ``n_devices``.  ``heads`` bounds the model
    axis; remaining devices fold into data parallelism (reference behavior:
    b = tpu_size / heads).  The pipeline axis (GPipe stages, ops/pipeline.py)
    is exactly ``cfg.pipeline_parallel``.  ``quiet`` suppresses the shrink
    warning — the elastic degraded-resume path replaces it with the mesh
    searcher's suggestion (reliability/dist.py::suggest_mesh)."""
    model = cfg.mesh_model
    seq = cfg.sequence_parallel
    pipe = cfg.pipeline_parallel
    denom = model * seq * pipe
    if n_devices % denom:
        # shrink the model axis to the largest divisor that fits
        model = 1
        for cand in range(min(cfg.mesh_model, n_devices), 0, -1):
            # the model axis must also divide the head count or head-sharded
            # parameters cannot be placed on the mesh
            if n_devices % (cand * seq * pipe) == 0 and cfg.heads % cand == 0:
                model = cand
                break
        denom = model * seq * pipe
        if n_devices % denom:
            raise ValueError(
                f"cannot factor {n_devices} devices into seq={seq} pipe={pipe}")
        if not quiet:
            print(f"WARNING: model axis shrunk from {cfg.mesh_model} to "
                  f"{model} to factor {n_devices} devices (seq={seq}, "
                  f"pipe={pipe}); `python tools/graftmesh.py --config "
                  f"<config> --world {n_devices}` searches the layout "
                  f"instead of folding")
    return {DATA_AXIS: n_devices // denom, SEQ_AXIS: seq, PIPE_AXIS: pipe,
            MODEL_AXIS: model}


def mesh_factorizations(cfg: Config, n_devices: int,
                        free_axes: typing.Sequence[str] = ()
                        ) -> typing.List[typing.Dict[str, int]]:
    """Every DP/SP/PP/TP axis-size assignment of ``n_devices`` this config
    could actually instantiate, in deterministic order — the enumeration
    space of the mesh searcher (analysis/mesh_search.py).

    Default constraints mirror :func:`axis_sizes`' degrees of freedom: the
    sequence and pipeline axes are STRUCTURAL declarations (they change the
    traced program — ring attention chunks, pipeline stage scans), so they
    stay pinned to the config's values while data x model placement varies.
    Passing axis names in ``free_axes`` (``sequence_parallel`` and/or
    ``pipeline``) unlocks them, subject to the validity rules config.py
    enforces: the model axis must divide ``heads`` (head-sharded params),
    the data axis must divide ``train_batch_size`` (make_mesh would drop
    surplus devices), a free sequence axis must divide ``sequence_length``
    (ring chunking), and a free pipeline axis must divide ``depth`` under a
    compatible memory-reduction strategy."""
    free = set(free_axes)
    unknown = free - {SEQ_AXIS, PIPE_AXIS}
    if unknown:
        raise ValueError(f"free_axes may name {SEQ_AXIS!r} and {PIPE_AXIS!r} "
                         f"only; got {sorted(unknown)}")

    def _divisors(n: int) -> typing.List[int]:
        return [d for d in range(1, n + 1) if n % d == 0]

    if SEQ_AXIS in free:
        seqs = [s for s in _divisors(n_devices)
                if cfg.sequence_length % s == 0]
    else:
        seqs = [cfg.sequence_parallel]
    if PIPE_AXIS in free:
        pipes = [p for p in _divisors(n_devices)
                 if p == 1 or (cfg.depth % p == 0 and not cfg.use_video
                               and cfg.memory_reduction_strategy
                               in ("none", "checkpoint"))]
    else:
        pipes = [cfg.pipeline_parallel]
    out: typing.List[typing.Dict[str, int]] = []
    for seq in seqs:
        for pipe in pipes:
            if seq > 1 and pipe > 1 and cfg.pipeline_schedule != "1f1b":
                continue  # config.py rejects the composition under gpipe
            rest = n_devices // (seq * pipe)
            if seq * pipe * rest != n_devices:
                continue
            for model in _divisors(rest):
                if model > cfg.heads or cfg.heads % model:
                    continue
                data = rest // model
                if cfg.train_batch_size % data:
                    continue
                out.append({DATA_AXIS: data, SEQ_AXIS: seq, PIPE_AXIS: pipe,
                            MODEL_AXIS: model})
    out.sort(key=lambda s: (s[DATA_AXIS], s[SEQ_AXIS], s[PIPE_AXIS],
                            s[MODEL_AXIS]))
    return out


def make_mesh(cfg: Config,
              devices: typing.Optional[typing.Sequence[jax.Device]] = None,
              quiet: bool = False) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    sizes = axis_sizes(cfg, len(devices), quiet=quiet)
    batch = cfg.train_batch_size
    if batch % sizes[DATA_AXIS]:
        # the data axis cannot exceed what the batch can shard over; drop to
        # the largest batch divisor and leave surplus devices out of the mesh
        data = max(d for d in range(1, sizes[DATA_AXIS] + 1)
                   if batch % d == 0)
        if not quiet:
            print(f"WARNING: data axis shrunk from {sizes[DATA_AXIS]} to {data} "
                  f"(train_batch_size={batch}); "
                  f"{(sizes[DATA_AXIS] - data) * sizes[SEQ_AXIS] * sizes[PIPE_AXIS] * sizes[MODEL_AXIS]}"
                  " device(s) left unused")
        sizes[DATA_AXIS] = data
    names = (DATA_AXIS, SEQ_AXIS, PIPE_AXIS, MODEL_AXIS)
    n_used = 1
    for n in names:
        n_used *= sizes[n]
    grid = np.asarray(devices[:n_used]).reshape([sizes[n] for n in names])
    return Mesh(grid, names)
