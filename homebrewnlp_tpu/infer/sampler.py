"""Autoregressive sampling.

Port of the reference's while-loop sampler (/root/reference/src/run/
inference.py:15-133) to ``jax.lax.while_loop``: each step rebuilds the full
forward pass (no KV cache — faithful to the reference, and required for
arbitrary DSL layers like bias-map mixer attention and cummean, whose state
is not a KV pair), samples via the Gumbel trick
``argmax(logits - T * log(-log(U)))`` (inference.py:88-92), shifts by one
position and blends the sampled token into ``token_x`` with a one-hot mask
(inference.py:94-96).  Temperature 0 reduces to greedy exactly as upstream.

The video variant blends generated frames back into the frame stream and
handles per-frame token sub-sequences (inference.py:25-73).
"""
from __future__ import annotations

import typing

import jax
import jax.numpy as jnp

from ..config import Config, SEQUENCE
from ..models import build
from ..models.ctx import Ctx
from ..nd import NT


def forward_logits(cfg: Config, params: dict, batch: typing.Dict[str, NT]
                   ) -> typing.Tuple[typing.Optional[jnp.ndarray],
                                     typing.Optional[jnp.ndarray]]:
    """One forward pass -> (token logits, frame output) raw arrays."""
    ctx = Ctx(cfg, params=params, train=False, rng=None)
    out = build(ctx, batch)
    tok = out.token_out.x if out.token_out is not None else None
    frame = out.frame_out.x if out.frame_out is not None else None
    return tok, frame


_logits = forward_logits


def jit_bound(fn, params):
    """jit ``fn(params, ...)`` and bind ``params`` as its first ARGUMENT.

    Closing over the weights instead would bake them into the program as
    HLO constants — hundreds of MB at real sizes, enough to exceed a
    remote-compile service's request limit, duplicated per compilation."""
    import functools
    return functools.partial(jax.jit(fn), params)


def _truncate_logits(logits: jnp.ndarray, top_k: int, top_p: float
                     ) -> jnp.ndarray:
    """Top-k / nucleus truncation (extension — the reference samples with
    temperature only, inference.py:88-92).  Masked entries go to -inf so the
    Gumbel trick can never pick them; ties at the threshold are all kept."""
    if top_p >= 1.0:  # top_k only: k-th threshold without a full vocab sort
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
        return jnp.where(logits < kth, -jnp.inf, logits)
    # one descending sort serves both cuts on the hot decode path
    desc = jnp.sort(logits, axis=-1)[..., ::-1]
    if top_k:
        desc = jnp.where(jnp.arange(desc.shape[-1]) < top_k, desc, -jnp.inf)
    probs = jax.nn.softmax(desc, axis=-1)  # top_k-masked entries carry 0 mass
    # keep tokens whose preceding cumulative mass is < p (the set always
    # includes the argmax and just crosses p)
    keep = ((jnp.cumsum(probs, axis=-1) - probs) < top_p) & jnp.isfinite(desc)
    thresh = jnp.min(jnp.where(keep, desc, jnp.inf), axis=-1, keepdims=True)
    return jnp.where(logits < thresh, -jnp.inf, logits)


def _gumbel_argmax(logits: jnp.ndarray, temperature, key: jax.Array,
                   top_k: int = 0, top_p: float = 1.0) -> jnp.ndarray:
    logits = logits.astype(jnp.float32)
    u = jax.random.uniform(key, logits.shape, jnp.float32, 1e-9, 1.0)
    gumbel = -jnp.log(-jnp.log(u))
    if top_k or top_p < 1.0:
        # standard truncation semantics: temper FIRST, then cut, then sample
        # at Gumbel scale 1 — so top_p measures cumulative mass on the
        # distribution actually being sampled (softmax(logits/T)), matching
        # the nucleus-sampling definition.  T=0 stays exact greedy.
        t = jnp.float32(temperature)
        hot = (t > 0).astype(jnp.float32)
        logits = _truncate_logits(logits / jnp.where(t > 0, t, 1.0),
                                  top_k, top_p)
        return jnp.argmax(logits + hot * gumbel, axis=-1).astype(jnp.int32)
    return jnp.argmax(logits + temperature * gumbel, axis=-1).astype(jnp.int32)


def _truncate_logits_lanes(logits: jnp.ndarray, top_k: jnp.ndarray,
                           top_p: jnp.ndarray) -> jnp.ndarray:
    """Per-lane TRACED truncation for the continuous-batching decode step
    (serve/engine.py): ``logits`` [lanes, ...], ``top_k`` int32 [lanes]
    (<=0 disables), ``top_p`` f32 [lanes] (>=1 disables).  Unlike
    :func:`_truncate_logits` the knobs are traced operands, so ONE
    compilation serves every request mix — at the cost of always paying
    the full descending sort (the k-th-threshold fast path needs a static
    k).  Semantics match the static path: temper first, cut, ties kept."""
    vocab = logits.shape[-1]
    side = (logits.shape[0],) + (1,) * (logits.ndim - 1)
    k = jnp.where(top_k > 0, top_k, vocab).reshape(side)
    p = top_p.astype(jnp.float32).reshape(side)
    desc = jnp.sort(logits, axis=-1)[..., ::-1]
    desc = jnp.where(jnp.arange(vocab) < k, desc, -jnp.inf)
    probs = jax.nn.softmax(desc, axis=-1)  # k-masked entries carry 0 mass
    keep = ((((jnp.cumsum(probs, axis=-1) - probs) < p) | (p >= 1.0))
            & jnp.isfinite(desc))
    thresh = jnp.min(jnp.where(keep, desc, jnp.inf), axis=-1, keepdims=True)
    return jnp.where(logits < thresh, -jnp.inf, logits)


def _gumbel_argmax_lanes(logits: jnp.ndarray, temperature: jnp.ndarray,
                         key: jax.Array, top_k: jnp.ndarray,
                         top_p: jnp.ndarray) -> jnp.ndarray:
    """:func:`_gumbel_argmax` with per-lane traced (temperature, top_k,
    top_p) [lanes] vectors — the batched decode step samples every lane's
    row under its own request's knobs in one compilation.  Lane
    temperature 0 stays exact greedy for that lane.

    ``key`` is a [lanes] key array: each lane draws its Gumbel noise from
    its OWN stream (serve/engine.py::lane_key), and a lane's draw covers
    exactly one row — the same element count as the serialized sampler's
    per-step draw, so the bits (and at temperature 1.0 the sampled
    tokens) match that path key-for-key."""
    logits = logits.astype(jnp.float32)
    side = (logits.shape[0],) + (1,) * (logits.ndim - 1)
    t = temperature.astype(jnp.float32).reshape(side)
    u = jax.vmap(lambda k: jax.random.uniform(
        k, logits.shape[1:], jnp.float32, 1e-9, 1.0))(key)
    gumbel = -jnp.log(-jnp.log(u))
    hot = (t > 0).astype(jnp.float32)
    tempered = logits / jnp.where(t > 0, t, 1.0)
    # the full-vocab sort only runs when SOME lane actually truncates —
    # both cond branches live in the one compilation, so the default
    # operating point (no truncation anywhere) skips the sort at runtime
    logits = jax.lax.cond(
        jnp.any((top_k > 0) | (top_p < 1.0)),
        lambda x: _truncate_logits_lanes(x, top_k, top_p),
        lambda x: x, tempered)
    return jnp.argmax(logits + hot * gumbel, axis=-1).astype(jnp.int32)


def _fire_token_row(callback, tag, fire: jnp.ndarray, pos, row: jnp.ndarray
                    ) -> None:
    """Host-notify one generated token row (docs/observability.md
    "Streaming and inter-token latency"): the streaming twin of
    :func:`_fire_first_token`, fired on EVERY written row instead of just
    the first.  The callback is UNORDERED — XLA may deliver rows out of
    sequence — so the payload carries the row position and the host sink
    reorders (``serve/interface.py::_RowStream``).  ``fire`` is a traced
    gate (the request's stream flag AND the row-write predicate): a
    non-streaming request pays one skipped cond per row, never a host
    round-trip, and ``callback=None`` at trace time keeps the graph
    byte-identical to the pre-streaming one."""
    jax.lax.cond(
        fire,
        lambda operands: jax.debug.callback(
            callback, jnp.asarray(tag, jnp.int32), operands[0], operands[1]),
        lambda operands: None,
        (jnp.asarray(pos, jnp.int32), row.reshape(-1)))


def _fire_first_token(callback, tag, fire: jnp.ndarray, token: jnp.ndarray
                      ) -> None:
    """Host-notify the first sampled token (docs/observability.md "Serving
    SLOs"): an UNORDERED ``jax.debug.callback`` gated by ``fire``, with the
    sampled token as an operand so XLA cannot hoist it ahead of the
    sampling computation it reports on.  ``tag`` is a TRACED request id —
    one compilation serves every request; the host side (the engine's
    dispatcher) resolves it to the per-request TTFT callback.  Fires at
    most once per sampler call by construction (``fire`` is true only on
    the first generated position)."""
    jax.lax.cond(
        fire,
        lambda t: jax.debug.callback(callback, jnp.asarray(tag, jnp.int32), t),
        lambda t: None,
        token.reshape(-1)[0])


def autoregressive_text(cfg: Config, params: dict, token_x: NT,
                        initial_pos: typing.Union[int, jnp.ndarray],
                        temperature: typing.Optional[float] = None,
                        end_iterations: typing.Optional[int] = None,
                        rng: typing.Optional[jax.Array] = None,
                        first_token_callback: typing.Optional[
                            typing.Callable] = None,
                        first_token_tag=0,
                        token_callback: typing.Optional[
                            typing.Callable] = None,
                        stream=0) -> jnp.ndarray:
    """Fill ``token_x`` from ``initial_pos`` to ``end_iterations``.

    ``token_x``: int NT [batch, sequence, token_patch].  Returns the filled
    int32 array of the same shape.  ``first_token_callback`` (host fn
    ``(tag, token)``), when given, is invoked from the graph exactly once —
    on the FIRST generated position — so serving can measure TTFT; with a
    full prompt (nothing to generate) it never fires.  None (the default,
    and every training/analysis path) keeps the pre-callback graph
    byte-identical — census goldens see no new equations.

    ``token_callback`` (host ``(tag, pos, row)``) is the streaming twin:
    every written row is host-notified while the loop still runs, gated by
    the TRACED ``stream`` flag — one compilation serves streaming and
    buffered requests alike, and requests with ``stream=0`` never pay a
    host round-trip."""
    temperature = (cfg.sampling_temperature if temperature is None
                   else temperature)
    end = cfg.sequence_length if end_iterations is None else end_iterations
    rng = jax.random.key(0) if rng is None else rng
    names = token_x.names
    seq_axis = names.index(SEQUENCE)
    pos0 = jnp.asarray(initial_pos, jnp.int32)

    batch_template = {"token_x": None,
                      "token_y": NT(jnp.zeros_like(token_x.x), names)}

    def body(carry):
        pos, toks, key = carry
        key, sub = jax.random.split(key)
        batch = dict(batch_template)
        batch["token_x"] = NT(toks, names)
        logits, _ = _logits(cfg, params, batch)  # [b, seq, patch, vocab]
        sampled = _gumbel_argmax(logits, jnp.float32(temperature), sub,
                                 cfg.sampling_top_k, cfg.sampling_top_p)
        # shift +1 along sequence (zero-fill, not wrap-around — reference
        # inference.py:94 shift(wrap=False)): position p receives the argmax
        # of the logits at p-1
        zeros = jnp.zeros_like(jax.lax.slice_in_dim(sampled, 0, 1, axis=seq_axis))
        sampled = jnp.concatenate(
            [zeros, jax.lax.slice_in_dim(sampled, 0, sampled.shape[seq_axis] - 1,
                                         axis=seq_axis)], axis=seq_axis)
        onehot = jax.nn.one_hot(pos, toks.shape[seq_axis], dtype=toks.dtype)
        onehot = onehot.reshape((1, toks.shape[seq_axis])
                                + (1,) * (toks.ndim - 2))
        new_toks = (sampled * onehot + toks * (1 - onehot)).astype(toks.dtype)
        if first_token_callback is not None:
            # the first loop iteration (pos == pos0) writes the first
            # generated row — this rebuild path's whole forward doubles as
            # the prompt "prefill", so TTFT covers it
            _fire_first_token(
                first_token_callback, first_token_tag, pos == pos0,
                jax.lax.dynamic_slice_in_dim(new_toks, pos, 1, seq_axis))
        if token_callback is not None:
            # every iteration writes row `pos`; streaming requests emit it
            _fire_token_row(
                token_callback, first_token_tag,
                jnp.asarray(stream, jnp.int32) != 0, pos,
                jax.lax.dynamic_slice_in_dim(new_toks, pos, 1, seq_axis))
        return pos + 1, new_toks, key

    def cond(carry):
        pos, _, _ = carry
        return pos < end

    _, out, _ = jax.lax.while_loop(
        cond, body, (jnp.asarray(initial_pos, jnp.int32),
                     token_x.x.astype(jnp.int32), rng))
    return out


def autoregressive_video(cfg: Config, params: dict,
                         batch: typing.Dict[str, NT],
                         initial_pos: typing.Optional[int] = None,
                         rng: typing.Optional[jax.Array] = None
                         ) -> typing.Tuple[typing.Optional[jnp.ndarray], jnp.ndarray]:
    """Video (jannet) sampling: generated frames are blended back into the
    input sequence; per-frame tokens are greedily decoded with padding-token
    masking (reference inference.py:25-73)."""
    pos0 = cfg.initial_autoregressive_position if initial_pos is None else initial_pos
    rng = jax.random.key(0) if rng is None else rng
    frame = batch["frame"]
    fnames = frame.names
    use_lang = cfg.use_language and "token_x" in batch

    def body(carry):
        pos, frame_x, tok_x, key = carry
        b = dict(batch)
        b["frame"] = NT(frame_x, fnames)
        if use_lang:
            b["token_x"] = NT(tok_x, batch["token_x"].names)
        tok_logits, frame_out = _logits(cfg, params, b)
        # frame_out covers positions [0, seq); write prediction for `pos`
        # (frame stream has seq+1 entries, prediction at pos-1 predicts pos)
        pad_width = [(0, 0)] * frame_x.ndim
        pad_width[1] = (1, 0)
        frame_pred = jnp.pad(frame_out.astype(frame_x.dtype), pad_width)
        onehot = jax.nn.one_hot(pos, frame_x.shape[1], dtype=frame_x.dtype)
        onehot = onehot.reshape((1, frame_x.shape[1]) + (1,) * (frame_x.ndim - 2))
        new_frame = frame_pred * onehot + frame_x * (1 - onehot)
        new_tok = tok_x
        if use_lang:
            sampled = jnp.argmax(tok_logits.astype(jnp.float32), -1).astype(
                tok_x.dtype)
            oh = onehot.reshape((1, frame_x.shape[1])
                                + (1,) * (tok_x.ndim - 2)).astype(tok_x.dtype)
            new_tok = sampled * oh + tok_x * (1 - oh)
        return pos + 1, new_frame, new_tok, key

    def cond(carry):
        pos = carry[0]
        return pos < cfg.time_patch_size

    tok0 = (batch["token_x"].x.astype(jnp.int32) if use_lang
            else jnp.zeros((), jnp.int32))
    _, frame_filled, tok_filled, _ = jax.lax.while_loop(
        cond, body, (jnp.asarray(pos0, jnp.int32),
                     frame.x.astype(cfg.calculation_dtype), tok0, rng))
    return (tok_filled if use_lang else None), frame_filled


def make_single_forward(cfg: Config, params: dict):
    """Non-autoregressive prediction (``use_autoregressive_sampling=False``,
    reference inference.py:136-170): ONE forward pass; positions from
    ``initial_pos`` up to ``end_iterations`` receive the one-step-ahead
    (teacher-forced) prediction, the prompt keeps its tokens.  Same signature
    as the autoregressive sampler so the engine can swap them.

    ``params`` ride as a jit ARGUMENT, not a closure: closed-over arrays
    become HLO constants, which duplicates the weights into the program
    (hundreds of MB at real sizes — enough to exceed a remote-compile
    service's request limit) and recompiles per weight set."""

    def fn(params, token_x: NT, initial_pos, temperature, rng,
           end_iterations=None):
        names = token_x.names
        seq_axis = names.index(SEQUENCE)
        toks = token_x.x.astype(jnp.int32)
        end = (jnp.int32(cfg.sequence_length) if end_iterations is None
               else end_iterations)
        batch = {"token_x": NT(toks, names),
                 "token_y": NT(jnp.zeros_like(toks), names)}
        logits, _ = _logits(cfg, params, batch)
        sampled = _gumbel_argmax(logits, jnp.float32(temperature), rng,
                                 cfg.sampling_top_k, cfg.sampling_top_p)
        zeros = jnp.zeros_like(jax.lax.slice_in_dim(sampled, 0, 1, axis=seq_axis))
        sampled = jnp.concatenate(
            [zeros, jax.lax.slice_in_dim(sampled, 0,
                                         sampled.shape[seq_axis] - 1,
                                         axis=seq_axis)], axis=seq_axis)
        pos = jnp.arange(toks.shape[seq_axis]).reshape(
            (1, toks.shape[seq_axis]) + (1,) * (toks.ndim - 2))
        keep = (pos < initial_pos) | (pos >= end)
        return jnp.where(keep, toks, sampled)

    return jit_bound(fn, params)


def make_text_sampler(cfg: Config, params: dict,
                      first_token_callback: typing.Optional[
                          typing.Callable] = None,
                      token_callback: typing.Optional[
                          typing.Callable] = None):
    """Jitted sampler: (token_x NT, initial_pos, temperature, rng,
    end_iterations[, first_token_tag[, stream]]) -> int32 tokens.
    initial_pos / temperature / end_iterations are traced so one
    compilation serves every prompt and response length (the reference
    feeds them via infeed placeholders,
    src/run/dataloader_placement.py:234-271).  ``params`` are a jit
    argument, not closed-over constants (see make_single_forward).

    ``first_token_callback`` (host ``(tag, token)``) arms the serving-SLO
    TTFT hook: the graph notifies the host once, at the first generated
    position, carrying the TRACED ``first_token_tag`` request id — one
    compilation serves every request (docs/observability.md).
    ``token_callback`` (host ``(tag, pos, row)``) arms per-row streaming
    the same way, runtime-gated by the traced ``stream`` flag — requests
    with ``stream=0`` share the compilation but never pay a host
    round-trip."""

    def fn(params, token_x: NT, initial_pos, temperature, rng,
           end_iterations=None, first_token_tag=0, stream=0):
        end = (jnp.int32(cfg.sequence_length) if end_iterations is None
               else end_iterations)
        return autoregressive_text(cfg, params, token_x, initial_pos,
                                   temperature, end_iterations=end, rng=rng,
                                   first_token_callback=first_token_callback,
                                   first_token_tag=first_token_tag,
                                   token_callback=token_callback,
                                   stream=stream)

    return jit_bound(fn, params)
