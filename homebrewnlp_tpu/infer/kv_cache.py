"""KV-cache incremental decoding — the inference fast path the reference
lacks (its while-loop sampler rebuilds the full forward per token,
/root/reference/src/run/inference.py:75-124; SURVEY.md §7 item 7 names the
cache as the intended improvement).

Eligibility: every sequence-mixing layer must be an ``attention`` layer —
causal ``dot_product`` (K/V cached) or the learned-map family
(``biased_softmax`` / ``biased_attention_map`` / ``scale_attention_map``,
the flagship mixer: V cached, map rows gathered per step —
models/layers.py::_cached_attention).  cumsum/cummean, convolution and
transpose_sequence_features carry different cross-position state and keep
the rebuild-everything sampler (infer/sampler.py).

The cached sampler PREFILLS the prompt with one full-length forward that
writes every prompt position's K/V at once, then runs one model call per
generated position on a length-1 row: attention layers write the row's K/V
into per-layer caches (models/layers.py::_cached_attention) and attend over
the cached prefix, so a full sample costs one full forward plus
O(generated) length-1 forwards instead of O(seq) full-length forwards.  Greedy (temperature 0) token outputs match the rebuild sampler:
both paths compute the same math, differing only in XLA fusion order, so
logits agree to float-rounding (measured <= 4e-3 absolute at seq 512 with
random weights, argmax identical at every teacher-forced position); a
randomly-initialized model whose top-2 logits tie within that noise can
still diverge mid-rollout.  Stochastic sampling draws an equivalent but
differently-shaped Gumbel noise stream.
"""
from __future__ import annotations

import typing

import jax
import jax.numpy as jnp

from ..config import Config, SEQUENCE
from ..models import build
from ..models.ctx import Ctx, DecodeState
from ..nd import NT
from .sampler import _gumbel_argmax
from ..sync import make_lock

_SEQUENCE_MIXERS = ("cumsum", "cummean", "convolution",
                    "transpose_sequence_features")
_MAP_FLAGS = ("biased_softmax", "biased_attention_map", "scale_attention_map")


def cache_eligible(cfg: Config) -> bool:
    """True when the config's whole layer stack decodes against a KV cache."""
    if cfg.use_video:
        return False
    # use_initial_position_embedding is cache-compatible: the body builds
    # the table full-length and slices the decoded rows at ctx.decode.pos
    # (models/__init__.py::_body), same as attention's positional keys
    for block in (list(cfg.input_block_config) + list(cfg.block_config)
                  + list(cfg.output_block_config)):
        for spec in block.layer:
            parts = spec.replace(":", "-").split("-")
            name = parts[0]
            if name in _SEQUENCE_MIXERS:
                return False
            if name == "attention":
                # dot_product caches K/V; the learned-map family caches V and
                # gathers map rows (flagship mixer).  input_as_value is
                # positionwise — cacheable under either.  An attention with
                # neither flag family raises in the layer itself.
                if "dot_product" not in parts and not any(
                        f in parts for f in _MAP_FLAGS):
                    return False
                if any(f in parts for f in _MAP_FLAGS) and 0 not in tuple(
                        cfg.masked_attention_dimensions):
                    # an UNMASKED map attends to future positions; the cache
                    # holds stale prefill values there while the rebuild
                    # sampler recomputes them per step — silent divergence,
                    # so unmasked map layers keep the rebuild path.  (The
                    # pure dot-product softmax is causal unconditionally,
                    # reference spatial.py:68, hence exempt.)
                    return False
    return True


def _decode_logits(cfg: Config, params: dict, row: jnp.ndarray,
                   pos, caches: typing.Dict[str, tuple], seq: int,
                   names: typing.Tuple[str, ...]
                   ) -> typing.Tuple[jnp.ndarray, typing.Dict[str, tuple]]:
    """One incremental step: logits for the single row at ``pos`` plus the
    updated caches."""
    dc = DecodeState(pos, dict(caches), seq)
    ctx = Ctx(cfg, params=params, train=False, rng=None, decode=dc)
    batch = {"token_x": NT(row, names),
             "token_y": NT(jnp.zeros_like(row), names)}
    out = build(ctx, batch)
    return out.token_out.x, dc.caches


def cache_shapes(cfg: Config, params: dict, batch_size: int,
                 seq: typing.Optional[int] = None
                 ) -> typing.Dict[str, tuple]:
    """Abstract per-layer cache shapes (``{layer: (ShapeDtypeStruct, ...)}``)
    for a ``batch_size`` x ``seq`` decode, discovered by abstract evaluation
    of one decode step — no FLOPs run and no memory allocated, so the static
    cost model (analysis/cost_model.py) prices serving KV HBM for any
    batch x context point without touching a device.  ``params`` may be
    ShapeDtypeStructs."""
    seq = cfg.sequence_length // cfg.token_patch_size if seq is None else seq
    names = ("batch", SEQUENCE, "language_token_patch")
    row = jax.ShapeDtypeStruct((batch_size, 1, cfg.token_patch_size), jnp.int32)

    def probe(params):
        return _decode_logits(cfg, params, jnp.zeros(row.shape, row.dtype),
                              jnp.int32(0), {}, seq, names)[1]

    return jax.eval_shape(probe, params)


def cache_nbytes(shapes: typing.Dict[str, tuple]) -> int:
    """Total bytes of a cache pytree from :func:`cache_shapes` — the
    KV-cache term of the per-device HBM prediction (caches follow the
    batch's data sharding, so divide by the data-axis size separately)."""
    import numpy as np
    total = 0
    for kv in shapes.values():
        for s in kv:
            n = 1
            for d in s.shape:
                n *= int(d)
            total += n * np.dtype(s.dtype).itemsize
    return int(total)


def init_caches(cfg: Config, params: dict, batch_size: int,
                seq: typing.Optional[int] = None
                ) -> typing.Dict[str, tuple]:
    """Zeroed cache pytree, discovered by abstract evaluation of one decode
    step (no FLOPs run)."""
    shapes = cache_shapes(cfg, params, batch_size, seq)
    return {k: tuple(jnp.zeros(s.shape, s.dtype) for s in kv)
            for k, kv in shapes.items()}


def block_rows(cfg: Config) -> int:
    """Decode rows (``token_patch_size`` tokens each) per KV-pool block.
    ``serve_block_tokens=0`` means one whole-sequence block, which makes
    the pool byte-identical to the monolithic per-lane cache."""
    rows = cfg.sequence_length // cfg.token_patch_size
    if not getattr(cfg, "serve_block_tokens", 0):
        return rows
    return max(1, min(rows, cfg.serve_block_tokens // cfg.token_patch_size))


def blocks_per_sequence(cfg: Config) -> int:
    """Blocks a full-length request occupies (admission takes the whole
    footprint up front — the engine never grows a request mid-decode)."""
    rows = cfg.sequence_length // cfg.token_patch_size
    return -(-rows // block_rows(cfg))


def pool_blocks(cfg: Config) -> int:
    """Effective pool capacity in blocks: ``serve_kv_blocks`` when set,
    else the physical pool (``serve_max_batch`` lanes x blocks/sequence)."""
    return (getattr(cfg, "serve_kv_blocks", 0)
            or getattr(cfg, "serve_max_batch", 1) * blocks_per_sequence(cfg))


def pool_shapes(cfg: Config, params: dict,
                seq: typing.Optional[int] = None) -> typing.Dict[str, tuple]:
    """Abstract shapes of the engine's pooled caches — ``cache_shapes`` at
    a batch of ``serve_max_batch`` lanes (``params`` may be
    ShapeDtypeStructs; nothing runs)."""
    return cache_shapes(cfg, params, getattr(cfg, "serve_max_batch", 1), seq)


def pool_nbytes(cfg: Config, params: dict,
                seq: typing.Optional[int] = None) -> int:
    """Bytes of the block-allocated KV pool under the serve knobs: the
    allocator's block geometry (``pool_blocks x block_rows``) times the
    per-row cache bytes summed over layers — the ``kv`` term the static
    cost model prices for serving (analysis/cost_model.py).  Defaults
    (one lane, whole-sequence blocks) equal the monolithic batch-1 cache
    exactly."""
    rows = (cfg.sequence_length // cfg.token_patch_size if seq is None
            else int(seq))
    per_row = cache_nbytes(cache_shapes(cfg, params, 1, rows)) / max(1, rows)
    return int(round(pool_blocks(cfg) * block_rows(cfg) * per_row))


def lane_view(caches: typing.Dict[str, tuple], lane) -> typing.Dict[str, tuple]:
    """One lane's rows of every pooled cache as batch-1 arrays
    (``dynamic_slice`` at a traced lane index) — the per-lane cache a
    chunk-granular prefill forward runs against
    (serve/engine.py::prefill_chunk_body)."""
    out = {}
    for name, kv in caches.items():
        out[name] = tuple(
            jax.lax.dynamic_slice(p, (lane,) + (0,) * (p.ndim - 1),
                                  (1,) + p.shape[1:])
            for p in kv)
    return out


def write_lane_rows(caches: typing.Dict[str, tuple],
                    lane_caches: typing.Dict[str, tuple],
                    lane, start_row, n_rows: int) -> typing.Dict[str, tuple]:
    """Scatter ``n_rows`` cache rows (sequence axis 1) of the batch-1
    ``lane_caches`` into lane ``lane`` of the pooled caches at row
    ``start_row`` — the chunk-granular write over the block pool: only the
    chunk's rows move, every other lane's (and the lane's own other) blocks
    are byte-untouched, so chunked and monolithic prefill leave identical
    cache prefixes."""
    out = {}
    for name, kv in caches.items():
        updated = []
        for pool, one in zip(kv, lane_caches[name]):
            rows = jax.lax.dynamic_slice_in_dim(one, start_row, n_rows, 1)
            updated.append(jax.lax.dynamic_update_slice(
                pool, jnp.asarray(rows, pool.dtype),
                (lane, start_row) + (0,) * (pool.ndim - 2)))
        out[name] = tuple(updated)
    return out


class BlockAllocator:
    """Fixed-capacity KV-pool accountant (docs/observability.md
    "Continuous batching"): ``n_blocks`` blocks of ``block_tokens`` tokens,
    handed out per request at ADMISSION (the whole footprint — prompt +
    response — is known up front, so a request never grows mid-decode) and
    recycled on completion.  Blocks are fungible — any block serves any
    lane — so the free list cannot fragment: an allocation succeeds iff
    enough blocks are free, regardless of the alloc/free history.

    Thread-safe: the scheduler thread allocates/frees while the admission
    path and the ``hbnlp_serve_kv_blocks_free`` gauge probe read."""

    def __init__(self, n_blocks: int, block_tokens: int):
        if n_blocks < 1:
            raise ValueError("BlockAllocator needs n_blocks >= 1")
        if block_tokens < 1:
            raise ValueError("BlockAllocator needs block_tokens >= 1")
        self.n_blocks = int(n_blocks)
        self.block_tokens = int(block_tokens)
        self._lock = make_lock("infer.kv_cache.BlockAllocator._lock")
        # LIFO free list: a finishing request's blocks go straight to the
        # next admission (warm reuse), and ids stay stable for tests
        self._free = list(range(self.n_blocks - 1, -1, -1))
        self._held: typing.Dict[typing.Hashable, typing.Tuple[int, ...]] = {}

    def blocks_needed(self, tokens: int) -> int:
        return max(1, -(-max(0, int(tokens)) // self.block_tokens))

    @property
    def free_blocks(self) -> int:
        with self._lock:
            return len(self._free)

    def held(self, owner: typing.Hashable) -> typing.Tuple[int, ...]:
        with self._lock:
            return self._held.get(owner, ())

    def fits(self, tokens: int) -> bool:
        """Whether a ``tokens``-long request could EVER be admitted (its
        footprint fits the whole pool) — the admission path sheds
        impossible requests immediately instead of queueing them forever."""
        return self.blocks_needed(tokens) <= self.n_blocks

    def alloc(self, owner: typing.Hashable, tokens: int
              ) -> typing.Optional[typing.Tuple[int, ...]]:
        """Take ``blocks_needed(tokens)`` blocks for ``owner``; None when
        the pool is too empty right now (caller keeps the request queued).
        One live allocation per owner."""
        need = self.blocks_needed(tokens)
        with self._lock:
            if owner in self._held:
                raise ValueError(f"owner {owner!r} already holds blocks")
            if need > len(self._free):
                return None
            ids = tuple(self._free.pop() for _ in range(need))
            self._held[owner] = ids
            return ids

    def free(self, owner: typing.Hashable) -> int:
        """Recycle ``owner``'s blocks; returns how many came back (0 for
        an unknown owner — freeing twice is a no-op, not a leak)."""
        with self._lock:
            ids = self._held.pop(owner, ())
            self._free.extend(ids)
            return len(ids)


def make_cached_text_sampler(cfg: Config, params: dict,
                             first_token_callback: typing.Optional[
                                 typing.Callable] = None,
                             token_callback: typing.Optional[
                                 typing.Callable] = None):
    """Jitted KV-cached sampler with the same signature as
    ``make_text_sampler``: (token_x NT, initial_pos, temperature, rng,
    end_iterations[, first_token_tag[, stream]]) -> int32 tokens.

    ``first_token_callback``: the serving-SLO TTFT hook (host
    ``(tag, token)``), fired exactly once — on the FIRST generated
    position, i.e. after the one-shot prompt prefill above has run — so
    TTFT measured here covers prefill + first incremental step, matching
    the rebuild sampler's semantics.  ``token_callback`` (host
    ``(tag, pos, row)``): the per-row streaming hook, fired on every
    written row when the traced ``stream`` flag is set (same traced-tag
    design — one compilation serves streaming and buffered requests)."""
    if not cache_eligible(cfg):
        raise ValueError("config is not KV-cache eligible; use make_text_sampler")

    def fn(params, token_x: NT, initial_pos, temperature, rng,
           end_iterations=None, first_token_tag=0, stream=0):
        names = token_x.names
        toks = token_x.x.astype(jnp.int32)
        seq_axis = names.index(SEQUENCE)
        assert seq_axis == 1, "cached decode expects [batch, sequence, patch]"
        seq = toks.shape[seq_axis]
        end = jnp.int32(seq) if end_iterations is None else end_iterations
        caches = init_caches(cfg, params, toks.shape[0], seq)
        # PREFILL: one full-length forward writes every position's K/V in a
        # single pass, so the incremental loop below starts at the end of the
        # prompt instead of decoding it token by token.  Rows past the prompt
        # hold padding K/V, but each is rewritten by the loop at its own
        # position before any later query can see it causally.  An empty
        # prompt (initial_pos 0) has nothing to prefill — the loop generates
        # every row anyway, so skip the full-length forward entirely.
        caches = jax.lax.cond(
            jnp.int32(initial_pos) > 0,
            lambda c: _decode_logits(cfg, params, toks, jnp.int32(0), c,
                                     seq, names)[1],
            lambda c: c, caches)
        start = jnp.maximum(jnp.int32(initial_pos) - 1, 0)

        def body(carry):
            pos, toks, caches, key = carry
            key, sub = jax.random.split(key)
            row = jax.lax.dynamic_slice_in_dim(toks, pos, 1, seq_axis)
            logits, caches = _decode_logits(cfg, params, row, pos, caches,
                                            seq, names)
            sampled = _gumbel_argmax(logits, jnp.float32(temperature), sub,
                                     cfg.sampling_top_k, cfg.sampling_top_p)
            # the sampled row is the prediction for position pos+1; write it
            # only into sampleable positions [initial_pos, end)
            nxt = pos + 1
            write = (nxt >= initial_pos) & (nxt < end) & (nxt < seq)
            cur = jax.lax.dynamic_slice_in_dim(toks, jnp.minimum(nxt, seq - 1),
                                               1, seq_axis)
            new_row = jnp.where(write, sampled.astype(toks.dtype), cur)
            toks = jax.lax.dynamic_update_slice_in_dim(
                toks, new_row, jnp.minimum(nxt, seq - 1), seq_axis)
            if first_token_callback is not None:
                # the first generated position is max(initial_pos, 1): the
                # loop starts one row early (start = initial_pos - 1) to
                # source the last prompt row's logits, and an empty prompt
                # generates from row 1 (row 0 is the random-pad seed row)
                from .sampler import _fire_first_token
                _fire_first_token(
                    first_token_callback, first_token_tag,
                    write & (nxt == jnp.maximum(jnp.int32(initial_pos), 1)),
                    new_row)
            if token_callback is not None:
                from .sampler import _fire_token_row
                _fire_token_row(
                    token_callback, first_token_tag,
                    write & (jnp.asarray(stream, jnp.int32) != 0),
                    nxt, new_row)
            return nxt, toks, caches, key

        def cond(carry):
            pos = carry[0]
            return pos < end - 1

        _, out, _, _ = jax.lax.while_loop(
            cond, body, (start, toks, caches, rng))
        return out

    from .sampler import jit_bound
    return jit_bound(fn, params)
