"""Inference layer: autoregressive while-loop samplers (JAX re-design of
/root/reference/src/run/inference.py)."""
from .sampler import (autoregressive_text, autoregressive_video,  # noqa: F401
                      make_text_sampler)
