"""Inference layer: autoregressive while-loop samplers (JAX re-design of
/root/reference/src/run/inference.py)."""
from .kv_cache import (cache_eligible, init_caches,  # noqa: F401
                       make_cached_text_sampler)
from .sampler import (autoregressive_text, autoregressive_video,  # noqa: F401
                      forward_logits, make_single_forward, make_text_sampler)
