"""Declared-lock factories + the HBNLP_SYNC_RECORD runtime recorder.

Every lock in the threaded host layer is created through :func:`make_lock` /
:func:`make_rlock` / :func:`make_condition` with its DECLARED name — the
same ``<module>.<Class>.<attr>`` identity the static concurrency model
(``analysis/concurrency.py``) derives from the source tree.  Normally the
factories return plain ``threading`` primitives (zero overhead); with
``HBNLP_SYNC_RECORD=1`` in the environment (or :func:`set_recording`) they
return recording proxies that log, per acquisition:

- **ordering edges**: for every lock already held by the acquiring thread,
  one ``held -> acquired`` edge — the runtime ground truth ``graftsync
  --validate`` pins against the static lock-order graph;
- **held-while-blocking** events: the acquire found the lock contended
  while the thread already held another lock (the precondition of every
  real deadlock);
- **held-while-joining** events: ``Thread.join`` called with any declared
  lock held (the classic shutdown deadlock — the joined thread may need
  that lock to exit).

Recorder tolerance (documented in docs/static_analysis.md): reentrant
re-acquisition of the SAME lock object (RLock, Condition) records no edge,
and two distinct instances sharing one declared name (per-request locks)
merge onto one graph node — a self-edge ``A -> A`` is therefore dropped
rather than reported.  Locks created BEFORE recording was enabled stay
plain and invisible; the subprocess runs ``graftsync --validate`` drives
set the env var so import-time module locks are covered too.

Stdlib-only by contract: ``tools/supervise.py`` loads this file standalone
(``_load_light``) so the recorder survives a broken jax install.
"""
from __future__ import annotations

import atexit
import json
import os
import threading
import typing

__all__ = ["make_lock", "make_rlock", "make_condition", "recording",
           "set_recording", "snapshot", "reset", "dump"]

# internal recorder state; guarded by a PLAIN lock that is itself never
# recorded (it would otherwise appear in every edge)
_STATE_LOCK = threading.Lock()
_TLS = threading.local()
_EDGES: typing.Set[typing.Tuple[str, str]] = set()
_BLOCKED: typing.List[dict] = []
_JOINS: typing.List[dict] = []
_FLAG = {"on": False}
_ORIG_JOIN = threading.Thread.join


def _held() -> list:
    held = getattr(_TLS, "held", None)
    if held is None:
        held = _TLS.held = []
    return held


def _record_edge(src: str, dst: str) -> None:
    if src == dst:
        return  # same declared name: reentrant or sibling instance
    with _STATE_LOCK:
        _EDGES.add((src, dst))


def _record_blocked(held: list, name: str) -> None:
    with _STATE_LOCK:
        _BLOCKED.append({"held": [n for _, n in held], "lock": name})


def _patched_join(self, timeout=None):
    held = getattr(_TLS, "held", None)
    if held:
        with _STATE_LOCK:
            _JOINS.append({"held": [n for _, n in held],
                           "thread": self.name})
    return _ORIG_JOIN(self, timeout)


class _RecordingLock:
    """Proxy over one threading primitive that maintains the per-thread
    held-lock stack and records ordering/blocking events.  Unknown
    attributes delegate to the inner lock, so ``Condition`` built on a
    proxied RLock keeps CPython's ``_is_owned``/``_release_save`` fast
    paths (wait()'s release/re-acquire bypasses the proxy, which is fine:
    no acquisition can happen on a thread parked in wait)."""

    def __init__(self, name: str, inner):
        self.name = name
        self._inner = inner

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        held = _held()
        reentrant = any(i == id(self) for i, _ in held)
        if held and not reentrant:
            for _, hname in held:
                _record_edge(hname, self.name)
        if blocking and held and not reentrant:
            got = self._inner.acquire(False)
            if not got:
                _record_blocked(held, self.name)
                got = (self._inner.acquire(True) if timeout == -1
                       else self._inner.acquire(True, timeout))
        else:
            got = (self._inner.acquire(blocking) if timeout == -1
                   else self._inner.acquire(blocking, timeout))
        if got:
            held.append((id(self), self.name))
        return got

    def release(self) -> None:
        held = _held()
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] == id(self):
                del held[i]
                break
        self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> bool:
        self.release()
        return False

    def locked(self) -> bool:
        return self._inner.locked()

    def __getattr__(self, item):
        return getattr(self._inner, item)

    def __repr__(self) -> str:
        return f"<RecordingLock {self.name} over {self._inner!r}>"


def recording() -> bool:
    return _FLAG["on"]


def set_recording(on: bool) -> None:
    """Toggle recording for locks created AFTER this call; also patches /
    unpatches ``Thread.join`` for held-while-joining detection.  Already-
    created plain locks stay plain (recorder tolerance — the subprocess
    validate runs set ``HBNLP_SYNC_RECORD=1`` before import instead)."""
    _FLAG["on"] = bool(on)
    threading.Thread.join = _patched_join if on else _ORIG_JOIN


def make_lock(name: str):
    """A ``threading.Lock`` under its declared graph name."""
    if _FLAG["on"]:
        return _RecordingLock(name, threading.Lock())
    return threading.Lock()


def make_rlock(name: str):
    """A ``threading.RLock`` under its declared graph name (reentrant
    re-acquires record no edge)."""
    if _FLAG["on"]:
        return _RecordingLock(name, threading.RLock())
    return threading.RLock()


def make_condition(name: str):
    """A ``threading.Condition`` whose underlying (reentrant) lock carries
    the declared name, so waits/notifies flow through the same held-stack
    accounting as plain acquisitions."""
    if _FLAG["on"]:
        return threading.Condition(lock=_RecordingLock(
            name, threading.RLock()))
    return threading.Condition()


def snapshot() -> dict:
    """Copy of everything recorded so far: sorted edge pairs, blocked
    events, join events."""
    with _STATE_LOCK:
        return {"edges": sorted(list(e) for e in _EDGES),
                "blocked": [dict(b) for b in _BLOCKED],
                "joins": [dict(j) for j in _JOINS]}


def reset() -> None:
    with _STATE_LOCK:
        _EDGES.clear()
        _BLOCKED.clear()
        _JOINS.clear()


def dump(path: str) -> None:
    """Append the recorded events to ``path`` as JSONL (one event per
    line; append-mode so subprocesses sharing a record file through the
    env var all land)."""
    snap = snapshot()
    lines = ([json.dumps({"kind": "edge", "src": a, "dst": b})
              for a, b in snap["edges"]]
             + [json.dumps({"kind": "blocked", **b})
                for b in snap["blocked"]]
             + [json.dumps({"kind": "join", **j}) for j in snap["joins"]])
    if not lines:
        return
    with open(path, "a") as f:
        f.write("\n".join(lines) + "\n")


def load_records(path: str) -> typing.List[dict]:
    """Parse a recorder JSONL file back into event dicts (torn tail lines
    from a killed process are skipped)."""
    out: typing.List[dict] = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except ValueError:
                    continue
    except OSError:
        pass
    return out


if os.environ.get("HBNLP_SYNC_RECORD", "") == "1":
    set_recording(True)
    _record_file = os.environ.get("HBNLP_SYNC_RECORD_FILE", "")
    if _record_file:
        atexit.register(dump, _record_file)
