"""Per-topology device constants for the static cost model (analysis/).

One small table, deliberately approximate: public per-chip HBM capacity,
HBM bandwidth, and ICI (inter-chip interconnect) bandwidth per mesh
direction, plus a per-collective latency constant for the alpha-beta
estimate.  The numbers exist so "does this config fit / what is it bound
by" can be answered BEFORE a ~2-minute TPU compile; they are calibrated
against measured ``memory_stats()`` peaks and XLA cost analysis by
bench.py's ``resources`` validation hook (``prediction_error`` rides the
BENCH trajectory), and tightened as that data accrues.

This module is a LEAF — no package imports — so ``config.py`` can validate
the ``target_device`` knob and ``analysis/cost_model.py`` can price a graph
without import cycles.  Peak FLOP/s stays in ``train/flops.py::PEAK_BF16``
(the live-MFU source of truth); ``tests/graftcost_test.py`` pins that every
kind here resolves there too.
"""
from __future__ import annotations

import dataclasses
import typing


@dataclasses.dataclass(frozen=True)
class DeviceSpec:
    kind: str  # canonical name, matched as a substring of device_kind
    hbm_bytes: int  # per-chip HBM capacity
    hbm_bw: float  # per-chip HBM bandwidth, bytes/s
    ici_bw: float  # per-link ICI bandwidth, bytes/s (one mesh direction)
    alpha_s: float = 1e-6  # per-collective launch/hop latency (alpha term)


_GIB = 1024 ** 3

#: Ordered like train/flops.py::PEAK_BF16 — more specific substrings first.
#: Sources: public TPU system specs; ici_bw is the per-direction figure the
#: alpha-beta model charges each mesh axis independently.
DEVICE_TABLE: typing.Tuple[DeviceSpec, ...] = (
    DeviceSpec("v6e", 32 * _GIB, 1640e9, 448e9),
    DeviceSpec("trillium", 32 * _GIB, 1640e9, 448e9),
    DeviceSpec("v5p", 95 * _GIB, 2765e9, 600e9),
    DeviceSpec("v5e", 16 * _GIB, 819e9, 200e9),
    DeviceSpec("v5 lite", 16 * _GIB, 819e9, 200e9),
    DeviceSpec("v5litepod", 16 * _GIB, 819e9, 200e9),
    DeviceSpec("v5", 95 * _GIB, 2765e9, 600e9),
    DeviceSpec("v4", 32 * _GIB, 1228e9, 300e9),
    DeviceSpec("v3", 32 * _GIB, 900e9, 162e9),
    DeviceSpec("v2", 16 * _GIB, 700e9, 62e9),
)

#: kinds tools/graftcost.py sweeps by default (one per HBM class)
SWEEP_KINDS = ("v5e", "v4", "v5p")


def resolve_device(kind: str) -> typing.Optional[DeviceSpec]:
    """Spec for a device kind (substring match, like
    ``train/flops.py::peak_flops``); None for CPU/unknown — no capacity or
    bandwidth claims are made there."""
    k = kind.lower()
    for spec in DEVICE_TABLE:
        if spec.kind in k:
            return spec
    return None


def known_kinds() -> typing.Tuple[str, ...]:
    return tuple(s.kind for s in DEVICE_TABLE)
