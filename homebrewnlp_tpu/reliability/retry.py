"""Retry/backoff for every I/O path that can flake.

The reference rides out GCS hiccups with ad-hoc loops (its TFRecord uploader
retries five times with exponential sleep, scripts/text2tfrecord.py:61-89);
here the policy is one object and one wrapper so every flaky call-site —
checkpoint save/restore, data-state sidecars, dataset opens, metric flushes —
shares the same semantics and the same observability:

- exponential backoff with multiplicative jitter (thundering-herd hygiene on
  a pod where every host restarts at once), capped per-try and by an optional
  wall-clock ``deadline_s`` across attempts;
- an explicit ``retryable`` exception tuple — a structure error or a typo
  must fail fast, only transport-shaped errors (OSError/TimeoutError) earn a
  retry by default;
- per-call-site counters in the obs registry (``hbnlp_io_retries_total`` /
  ``hbnlp_io_giveups_total``, labelled by ``site``) so /metrics shows which
  dependency is degrading long before it kills a run.

Fault-injection note: :mod:`~homebrewnlp_tpu.reliability.faults` raises
``FaultInjectedIOError`` (an ``OSError``) at instrumented sites, so injected
storage failures exercise exactly this retry path end-to-end.
"""
from __future__ import annotations

import dataclasses
import functools
import logging
import random
import time
import typing

try:
    from ..obs.registry import REGISTRY, MetricsRegistry
except ImportError:  # loaded by file path (tools/graftserve.py _load_light)
    import importlib.util as _ilu
    import os as _os
    import sys as _sys
    _reg = (_sys.modules.get("homebrewnlp_tpu.obs.registry")
            or _sys.modules.get("hbnlp_obs_registry"))
    if _reg is None:
        _spec = _ilu.spec_from_file_location(
            "hbnlp_obs_registry",
            _os.path.join(_os.path.dirname(_os.path.abspath(__file__)),
                          _os.pardir, "obs", "registry.py"))
        _reg = _ilu.module_from_spec(_spec)
        _spec.loader.exec_module(_reg)
        _sys.modules["hbnlp_obs_registry"] = _reg
    REGISTRY, MetricsRegistry = _reg.REGISTRY, _reg.MetricsRegistry

LOG = logging.getLogger("homebrewnlp_tpu.reliability")


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """How a call-site retries.  ``max_attempts`` counts the first try."""

    max_attempts: int = 4
    base_delay_s: float = 0.1
    max_delay_s: float = 30.0
    jitter: float = 0.25          # +/- fraction applied to each delay
    deadline_s: typing.Optional[float] = None  # wall budget across attempts
    retryable: typing.Tuple[type, ...] = (OSError, TimeoutError)

    def delay(self, attempt: int,
              rng: typing.Callable[[], float] = random.random) -> float:
        """Backoff before retry number ``attempt`` (0-based)."""
        d = min(self.base_delay_s * (2.0 ** attempt), self.max_delay_s)
        if self.jitter:
            d *= 1.0 + self.jitter * (2.0 * rng() - 1.0)
        return max(0.0, d)


DEFAULT_POLICY = RetryPolicy()
#: metric flushes: tiny budget — a wedged disk must not stall the step loop
FLUSH_POLICY = RetryPolicy(max_attempts=3, base_delay_s=0.05, max_delay_s=0.5)


def retry_call(fn: typing.Callable[[], typing.Any], *, site: str,
               policy: RetryPolicy = DEFAULT_POLICY,
               registry: typing.Optional[MetricsRegistry] = None,
               sleep: typing.Callable[[float], None] = time.sleep
               ) -> typing.Any:
    """Call ``fn`` under ``policy``; re-raise the last error on give-up.

    ``site`` labels the retry/give-up counters and the log lines — name the
    dependency, not the function (``ckpt_write``, ``data_open``)."""
    reg = REGISTRY if registry is None else registry
    retries = reg.counter("hbnlp_io_retries_total",
                          "I/O retries by call-site", labelnames=("site",))
    giveups = reg.counter("hbnlp_io_giveups_total",
                          "I/O retry budgets exhausted by call-site",
                          labelnames=("site",))
    start = time.monotonic()
    attempt = 0
    while True:
        try:
            return fn()
        except policy.retryable as e:
            attempt += 1
            spent = time.monotonic() - start
            exhausted = attempt >= policy.max_attempts or (
                policy.deadline_s is not None and spent >= policy.deadline_s)
            if exhausted:
                giveups.labels(site=site).inc()
                LOG.error("%s failed %d time(s) in %.1fs; giving up: %r",
                          site, attempt, spent, e)
                raise
            d = policy.delay(attempt - 1)
            if policy.deadline_s is not None:
                d = min(d, max(0.0, policy.deadline_s - spent))
            retries.labels(site=site).inc()
            LOG.warning("%s failed (attempt %d/%d): %r; retrying in %.2fs",
                        site, attempt, policy.max_attempts, e, d)
            sleep(d)


def retrying(site: str, policy: RetryPolicy = DEFAULT_POLICY,
             registry: typing.Optional[MetricsRegistry] = None):
    """Decorator form of :func:`retry_call`."""
    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            return retry_call(lambda: fn(*args, **kwargs), site=site,
                              policy=policy, registry=registry)
        return wrapper
    return deco
