"""Elastic multi-host coordination: retried cluster init, barriers, peer loss.

The reference's TF1 session + SimdMeshImpl assume a FIXED device assignment
for the run's lifetime — a single preempted host kills the whole pod job
permanently.  Here multi-host membership is an input the run negotiates
(docs/reliability.md "Multi-host elasticity"):

- :func:`initialize` wraps ``jax.distributed.initialize`` in the existing
  :class:`~homebrewnlp_tpu.reliability.retry.RetryPolicy` — a coordinator
  that is still coming up (the usual fleet-relaunch race) earns exponential
  backoff bounded by ``dist_init_timeout_s`` instead of an instant crash;
  retries count on ``hbnlp_dist_init_retries_total`` and the final join time
  lands on the ``hbnlp_dist_init_seconds`` gauge.
- :func:`barrier` is the barrier-with-timeout primitive over the distributed
  runtime's KV service (single-process: no-op); a peer that never shows up
  surfaces as :class:`BarrierTimeout` (a :class:`PeerLost`) instead of an
  unbounded hang.
- :func:`check_peers` is polled by the train loop every update: the fault
  sites ``peer`` / ``coordinator`` (``peer:die@step10``,
  ``coordinator:drop@step5``) raise :class:`PeerLost` /
  :class:`CoordinatorLost` so the whole detection -> checkpoint ->
  ``EXIT_PEER_LOST`` (87) -> lockstep fleet relaunch story is chaos-testable
  on CPU.  On real clusters the same exception classes wrap barrier
  timeouts and init give-ups — any host observing a peer failure cuts a
  checkpoint and exits 87, and the per-host supervisors
  (tools/supervise.py) relaunch the *fleet* together instead of letting one
  host spin alone against a dead collective.

Rank/coordinator plumbing: config knobs ``dist_coordinator`` /
``dist_num_processes`` / ``dist_process_id`` are overridden by the env vars
``HBNLP_DIST_COORDINATOR`` / ``HBNLP_DIST_NUM_PROCESSES`` /
``HBNLP_DIST_PROCESS_ID`` so ONE config file serves every host — the
supervisor injects the per-host rank into its child's environment.
"""
from __future__ import annotations

import dataclasses
import logging
import os
import time
import typing

from ..obs import spans
from ..obs.registry import REGISTRY, MetricsRegistry
from . import faults
from .retry import RetryPolicy, retry_call

LOG = logging.getLogger("homebrewnlp_tpu.reliability.dist")

ENV_COORDINATOR = "HBNLP_DIST_COORDINATOR"
ENV_NUM_PROCESSES = "HBNLP_DIST_NUM_PROCESSES"
ENV_PROCESS_ID = "HBNLP_DIST_PROCESS_ID"


class DistributedFailure(RuntimeError):
    """A multi-host failure this host detected (peer death, coordinator
    loss, barrier timeout).  The train loop cuts a checkpoint and exits
    ``EXIT_PEER_LOST`` (87) so the supervisor fleet relaunches in lockstep."""


class PeerLost(DistributedFailure):
    """Another host of the fleet died (or never arrived at a barrier)."""


class CoordinatorLost(DistributedFailure):
    """The jax.distributed coordinator is unreachable (init retries
    exhausted, or the connection dropped mid-run)."""


class BarrierTimeout(PeerLost):
    """A fleet barrier expired before every host arrived."""


@dataclasses.dataclass(frozen=True)
class DistSettings:
    """Resolved multi-host settings (env overrides config — see module
    docstring)."""
    coordinator: str
    num_processes: int
    process_id: int
    init_timeout_s: float = 300.0
    init_retries: int = 3
    barrier_timeout_s: float = 60.0

    @property
    def attempt_timeout_s(self) -> int:
        """Per-attempt ``initialization_timeout`` slice of the overall
        deadline: a slow coordinator must not consume the whole budget on
        attempt 1, or the retry counter never engages for exactly the
        failure mode it exists to survive."""
        if not self.init_timeout_s:
            return 300  # jax's own default join timeout
        return max(10, int(self.init_timeout_s / (self.init_retries + 1)))


class _InitCrash(Exception):
    """Non-retryable envelope for an injected ``dist_init:die`` crash (see
    initialize(): bare RuntimeError must stay retryable for real
    XlaRuntimeError init failures)."""

    def __init__(self, crash: BaseException):
        super().__init__(str(crash))
        self.crash = crash


#: module state: initialize() is once-per-process (jax.distributed refuses a
#: second init); tests reset via _reset_for_tests()
_STATE: typing.Dict[str, typing.Any] = {
    "initialized": False, "settings": None, "init_seconds": None}


def settings(cfg=None) -> typing.Optional[DistSettings]:
    """Resolve distributed settings from env (first) and ``cfg`` (second).
    Returns None when no multi-host world is configured (num_processes <= 1
    everywhere) — the single-host path stays byte-identical."""
    coord = os.environ.get(ENV_COORDINATOR,
                           getattr(cfg, "dist_coordinator", "") or "")
    nproc = int(os.environ.get(ENV_NUM_PROCESSES,
                               getattr(cfg, "dist_num_processes", 0) or 0))
    rank = int(os.environ.get(ENV_PROCESS_ID,
                              getattr(cfg, "dist_process_id", 0) or 0))
    if nproc <= 0 or (nproc == 1 and not coord):
        # no world configured; an EXPLICIT coordinator with nproc=1 (the
        # legacy --tpu addr,0,1 single-process pod slice) still initializes
        # so the distributed runtime comes up exactly as it always did
        return None
    if not coord:
        raise ValueError(
            f"dist_num_processes={nproc} but no coordinator address: set "
            f"dist_coordinator (or {ENV_COORDINATOR}) to host:port")
    if not 0 <= rank < nproc:
        raise ValueError(
            f"dist_process_id={rank} out of range for "
            f"dist_num_processes={nproc}")
    return DistSettings(
        coordinator=coord, num_processes=nproc, process_id=rank,
        init_timeout_s=float(getattr(cfg, "dist_init_timeout_s", 300.0)),
        init_retries=int(getattr(cfg, "dist_init_retries", 3)),
        barrier_timeout_s=float(getattr(cfg, "dist_barrier_timeout_s", 60.0)))


def _jax_initialize(s: DistSettings) -> None:
    import jax
    try:
        jax.distributed.initialize(
            s.coordinator, num_processes=s.num_processes,
            process_id=s.process_id,
            initialization_timeout=s.attempt_timeout_s)
    except TypeError:
        # older jax without the initialization_timeout kwarg
        jax.distributed.initialize(
            s.coordinator, num_processes=s.num_processes,
            process_id=s.process_id)


def initialize(cfg=None, *,
               registry: typing.Optional[MetricsRegistry] = None,
               init_fn: typing.Optional[
                   typing.Callable[[DistSettings], None]] = None,
               sleep: typing.Callable[[float], None] = time.sleep
               ) -> typing.Optional[float]:
    """Join the jax.distributed cluster under the retry policy.

    Returns the join time in seconds, or None when no multi-host world is
    configured.  A coordinator that stays unreachable past the retry budget
    (or ``dist_init_timeout_s``) raises :class:`CoordinatorLost` — the
    caller exits ``EXIT_PEER_LOST`` so the supervisor fleet retries the
    relaunch together rather than crash-looping one host.

    The fault site ``dist_init`` fires inside each attempt, so
    ``dist_init:fail@1`` drills exactly this retry path."""
    s = settings(cfg)
    if s is None:
        return None
    if _STATE["initialized"]:
        # idempotent: main() initializes for every run mode and train()
        # re-checks for direct callers — the second call is expected
        LOG.info("jax.distributed already initialized (rank %d/%d); "
                 "keeping the existing cluster membership",
                 _STATE["settings"].process_id,
                 _STATE["settings"].num_processes)
        return _STATE["init_seconds"]
    reg = REGISTRY if registry is None else registry
    retries = reg.counter(
        "hbnlp_dist_init_retries_total",
        "jax.distributed.initialize attempts retried (coordinator "
        "unreachable or injected dist_init fault)")
    # real jax.distributed failures surface as jaxlib XlaRuntimeError (a
    # RuntimeError), not OSError — a coordinator still coming up after a
    # fleet relaunch MUST earn the backoff, so RuntimeError is retryable
    # here (bounded by attempts + deadline; config typos raise ValueError,
    # which still fails fast)
    policy = RetryPolicy(
        max_attempts=s.init_retries + 1, base_delay_s=1.0, max_delay_s=15.0,
        deadline_s=s.init_timeout_s or None,
        retryable=(OSError, TimeoutError, RuntimeError))

    def _connect() -> None:
        try:
            faults.hit("dist_init")
        except faults.FaultInjectedCrash as e:
            # 'die' is documented NON-retryable, but it subclasses
            # RuntimeError which this policy (rightly) retries for real
            # XlaRuntimeError init failures — smuggle it past the retry
            # loop so the drill kills the process like a real bug would
            raise _InitCrash(e) from e
        (init_fn or _jax_initialize)(s)

    t0 = time.monotonic()
    try:
        retry_call(_connect, site="dist_init", policy=policy, registry=reg,
                   sleep=lambda d: (retries.inc(), sleep(d)))
    except _InitCrash as e:
        raise e.crash
    except policy.retryable as e:
        raise CoordinatorLost(
            f"jax.distributed.initialize({s.coordinator!r}, rank "
            f"{s.process_id}/{s.num_processes}) failed after "
            f"{s.init_retries + 1} attempt(s) / {s.init_timeout_s:.0f}s "
            f"deadline: {e}") from e
    elapsed = time.monotonic() - t0
    _STATE.update(initialized=True, settings=s, init_seconds=elapsed)
    reg.gauge("hbnlp_dist_init_seconds",
              "wall seconds jax.distributed.initialize took to join the "
              "cluster (the elastic-recovery cost of a fleet relaunch)",
              fn=lambda: _STATE["init_seconds"] or 0.0)
    LOG.info("joined distributed cluster as rank %d/%d via %s in %.2fs",
             s.process_id, s.num_processes, s.coordinator, elapsed)
    return elapsed


def active() -> bool:
    return bool(_STATE["initialized"])


def init_seconds() -> typing.Optional[float]:
    return _STATE["init_seconds"]


def barrier(name: str, timeout_s: typing.Optional[float] = None) -> None:
    """Wait until every process reaches the named barrier, bounded by
    ``timeout_s`` (default: the resolved ``dist_barrier_timeout_s``).

    Single-process (or before :func:`initialize`): no-op.  A timeout raises
    :class:`BarrierTimeout` — a missing peer must surface as a peer-lost
    exit (87), never an unbounded hang the watchdog can only observe."""
    import jax
    if jax.process_count() <= 1:
        return
    s = _STATE["settings"]
    if timeout_s is None:
        timeout_s = s.barrier_timeout_s if s is not None else 60.0
    client = None
    try:
        from jax._src import distributed as _jdist
        client = getattr(_jdist.global_state, "client", None)
    except Exception:  # pragma: no cover - jax internals moved
        client = None
    if client is not None and hasattr(client, "wait_at_barrier"):
        try:
            # the span pair is the fleet trace merge's clock reference:
            # every rank LEAVES a barrier at nearly the same true instant,
            # so matching span END times across ranks carry the inter-rank
            # clock offset (obs/fleet.py::estimate_offsets).  Ambient no-op
            # when spans are off — the single-host path pays nothing.
            with spans.span("dist/barrier", barrier=name):
                client.wait_at_barrier(name, int(timeout_s * 1000))
            return
        except Exception as e:
            raise BarrierTimeout(
                f"barrier {name!r} expired after {timeout_s:.0f}s — a peer "
                f"never arrived ({type(e).__name__}: {e})") from e
    # no KV client (unusual toolchain): fall back to the device-level sync,
    # which has no timeout — log so a hang here is attributable
    LOG.warning("distributed runtime exposes no wait_at_barrier; barrier "
                "%r falls back to sync_global_devices (no timeout)", name)
    from jax.experimental import multihost_utils
    with spans.span("dist/barrier", barrier=name):
        multihost_utils.sync_global_devices(name)


def check_peers(step: int) -> None:
    """Poll the distributed fault sites for this global step (train loop,
    once per update; inert without an armed plan).

    ``peer:die@stepN`` raises :class:`PeerLost`; ``coordinator:drop@stepN``
    raises :class:`CoordinatorLost`.  Both are caller-implemented actions
    (like ``grads:nan``): the site is the detection point, the loop's
    reaction — checkpoint, then ``EXIT_PEER_LOST`` — is the code under
    test."""
    for action in faults.take("peer", value=step):
        if action == "die":
            raise PeerLost(f"peer host lost at step {step} (injected)")
        LOG.error("peer fault site: unsupported action %r ignored", action)
    for action in faults.take("coordinator", value=step):
        if action == "drop":
            raise CoordinatorLost(
                f"coordinator connection dropped at step {step} (injected)")
        LOG.error("coordinator fault site: unsupported action %r ignored",
                  action)


#: env kill-switch for the degraded-resume mesh search (the suggestion costs
#: one abstract trace — seconds; "0" skips it)
ENV_MESH_SUGGEST = "HBNLP_MESH_SUGGEST"


def suggest_mesh(cfg, world_size: int, *,
                 device_kind: str = "", traces=None):
    """World-size renegotiation consults the mesh searcher
    (analysis/mesh_search.py): the best DP/SP/PP/TP factorization of
    ``world_size`` devices for this config under its declared structure,
    plus the predicted step-time delta vs the ``axis_sizes`` fallback the
    runtime would otherwise silently build.

    Returns a :class:`~homebrewnlp_tpu.analysis.mesh_search.MeshSuggestion`,
    or None when the search cannot run (declared seq x pipe structure does
    not factor the world, unpriceable device, or ``HBNLP_MESH_SUGGEST=0``)
    — those cases stay operator-assisted, as docs/reliability.md documents
    for coordinator-mode fleets."""
    if os.environ.get(ENV_MESH_SUGGEST, "1") == "0":
        return None
    from ..analysis import mesh_search  # lazy: jax-heavy, resume-path only
    try:
        return mesh_search.suggest(
            cfg, world_size, device_kind=device_kind, traces=traces,
            config_name=os.path.basename(
                str(getattr(cfg, "model_path", "") or "config")))
    except Exception as e:
        LOG.warning("mesh search for world_size=%d unavailable (%s: %s); "
                    "falling back to the folded axis_sizes mesh",
                    world_size, type(e).__name__, e)
        return None


def log_mesh_suggestion(cfg, mesh, n_devices: typing.Optional[int] = None
                        ) -> typing.Optional[typing.Any]:
    """Degraded-resume replacement for the old "axis shrunk" fold warnings:
    log the searcher's chosen mesh and its predicted step-time delta vs the
    mesh actually built.  ``n_devices`` is the AVAILABLE device count (the
    world the searcher factors) — it can exceed ``mesh.size`` when the
    batch-bound data axis dropped devices out of the built mesh.
    Best-effort — never raises, returns the suggestion (or None) so
    callers/tests can inspect it."""
    world = int(n_devices) if n_devices else int(mesh.size)
    try:
        suggestion = suggest_mesh(cfg, world)
    except Exception:  # pragma: no cover - suggest_mesh already guards
        return None
    built = {k: int(v) for k, v in dict(mesh.shape).items()}
    unused = ""
    if world > int(mesh.size):
        unused = (f" ({world - int(mesh.size)} of {world} device(s) left "
                  f"out of the built mesh)")
    if suggestion is None:
        LOG.warning(
            "resuming degraded on %d device(s) (tpu_size=%d) with mesh "
            "%s%s; no searched suggestion available", world,
            int(getattr(cfg, "tpu_size", 1)), built, unused)
        return None
    LOG.warning(
        "resuming degraded on %d device(s) (tpu_size=%d), built mesh %s%s; "
        "%s", world, int(getattr(cfg, "tpu_size", 1)), built, unused,
        suggestion.describe())
    return suggestion


def _reset_for_tests() -> None:
    _STATE.update(initialized=False, settings=None, init_seconds=None)
