"""Fault-injection harness: an env/config-driven plan of deliberate failures.

The recovery paths this PR adds (manifest fallback, retry/backoff, grace
checkpoints, supervisor relaunch) are exactly the code that never runs in a
healthy CI — so they rot.  A *fault plan* arms deterministic failures at
instrumented sites and the chaos suite (tests/reliability_test.py, the CI
``chaos`` job) proves each recovery end-to-end with bit-identical losses
after resume.

Grammar (``cfg.fault_plan`` or the ``HBNLP_FAULT_PLAN`` env var)::

    plan    := entry (';' entry)*
    entry   := [site ':'] action '@' trigger
    trigger := ['step' | 'req'] integer  # "step25" == "25", "req5" == "5"

An entry without a site rides the ``step`` site (so ``sigterm@step25`` reads
naturally).  Each rule fires **once**.  Sites instrumented today:

- ``step``        — per update in the train loop; trigger matches the GLOBAL
                    step counter (survives resume), not a per-run count
- ``ckpt_write``  — per checkpoint commit attempt (before the orbax write)
- ``ckpt_commit`` — after a successful commit (``path`` = the step dir)
- ``feeder``      — per batch in the DeviceFeeder producer thread
- ``data_read``   — per record pulled from a TFRecord shard (text AND video
                    pipelines)
- ``grads``       — per update, polled by the train loop via :func:`take`
                    (trigger matches the global step counter); the loop
                    implements the action itself
- ``dist_init``   — per ``jax.distributed.initialize`` attempt
                    (reliability/dist.py): ``dist_init:fail@1`` makes the
                    first join attempt fail, exercising the coordinator-
                    unreachable retry/backoff path
- ``peer``        — per update, polled via :func:`take` against the global
                    step (``peer:die@step10`` simulates observing a peer
                    host's death: checkpoint + ``EXIT_PEER_LOST``)
- ``coordinator`` — per update, polled via :func:`take` against the global
                    step (``coordinator:drop@step5`` simulates losing the
                    jax.distributed coordinator mid-run)
- ``serve_step``  — per continuous-batching scheduler iteration that has
                    work (serve/engine.py), polled via :func:`take`; the
                    loop implements the action: ``serve_step:fail@N``
                    raises into the fail-everything path (in-flight
                    requests 500, the engine keeps serving),
                    ``serve_step:stall@N`` wedges the loop long enough to
                    trip the decode-loop watchdog (``HBNLP_SERVE_STALL_S``
                    overrides the default 2 s sleep)
- ``replica``     — per completion request in the REST handler
                    (serve/rest.py), polled via :func:`take`:
                    ``replica:die@reqN`` hard-kills the serving process at
                    its Nth completion request (``os._exit`` — connection
                    reset mid-request, exactly what the router's failover
                    must absorb); ``replica:wedge_healthz@N`` wedges the
                    obs exporter's /healthz so the router's poll timeout,
                    not a clean 503, has to catch it

Actions:

- ``fail``    — raise :class:`FaultInjectedIOError` (an ``OSError``): flows
                through the retry layer like a real storage error
- ``die``     — raise :class:`FaultInjectedCrash` (``RuntimeError``): NOT
                retryable, kills the enclosing actor like a real bug
- ``sigterm`` / ``sigint`` — deliver the signal to this process (preemption)
- ``corrupt`` — bit-flip the largest file under the site's ``path`` kwarg
                (``ckpt_commit:corrupt@1`` tears the freshest checkpoint)
- ``nan``     — caller-implemented (``take`` sites only): the train loop
                feeds a NaN gradient scale into the step so the device-
                telemetry anomaly path is drillable (``grads:nan@step3``)
                without permanently poisoning parameters; requires
                ``telemetry_interval > 0``
- ``drop``    — caller-implemented (``take`` sites only): the train loop's
                distributed poll (reliability/dist.py::check_peers) raises
                ``CoordinatorLost`` — ``coordinator:drop@step5``
- ``stall``   — caller-implemented (``take`` sites only): the serving
                scheduler loop sleeps past its watchdog threshold
                (``serve_step:stall@N``)
- ``wedge_healthz`` — caller-implemented (``take`` sites only): the
                serving health snapshot hangs so /healthz stops answering
                (``replica:wedge_healthz@N``)

Example: ``fault_plan="ckpt_write:fail@2;feeder:die@step10;sigterm@step25"``
fails the 2nd checkpoint write once (retried), kills the feeder thread at
its 10th batch, and preempts the run at global step 25.
"""
from __future__ import annotations

import dataclasses
import logging
import os
import signal as signal_mod
import threading
import typing

from ..sync import make_lock

LOG = logging.getLogger("homebrewnlp_tpu.reliability.faults")

ACTIONS = ("fail", "die", "sigterm", "sigint", "corrupt", "nan", "drop",
           "stall", "wedge_healthz")
#: bare actions (no explicit site) ride the train-step site
DEFAULT_SITE = "step"


class FaultInjected(Exception):
    """Marker mixin: every injected fault is recognizable in logs/tests."""


class FaultInjectedIOError(FaultInjected, OSError):
    """Retryable injected failure (flows through reliability.retry)."""


class FaultInjectedCrash(FaultInjected, RuntimeError):
    """Non-retryable injected failure (kills the enclosing actor)."""


@dataclasses.dataclass
class FaultRule:
    site: str
    action: str
    at: int
    fired: bool = False

    def __str__(self) -> str:
        return f"{self.site}:{self.action}@{self.at}"


def parse_plan(spec: typing.Optional[str]) -> typing.List[FaultRule]:
    """Parse the plan grammar; raises ``ValueError`` with the bad entry."""
    rules: typing.List[FaultRule] = []
    for entry in (spec or "").split(";"):
        entry = entry.strip()
        if not entry:
            continue
        if "@" not in entry:
            raise ValueError(f"fault plan entry {entry!r}: expected "
                             "[site:]action@trigger")
        left, trigger = entry.rsplit("@", 1)
        trigger = trigger.strip()
        for prefix in ("step", "req"):  # "die@step10" / "replica:die@req5"
            if trigger.startswith(prefix):
                trigger = trigger[len(prefix):]
                break
        try:
            at = int(trigger)
        except ValueError:
            raise ValueError(f"fault plan entry {entry!r}: trigger must be "
                             "an integer (optionally 'step'/'req'-prefixed)")
        if ":" in left:
            site, action = (p.strip() for p in left.split(":", 1))
        else:
            site, action = DEFAULT_SITE, left.strip()
        if action not in ACTIONS:
            raise ValueError(f"fault plan entry {entry!r}: unknown action "
                             f"{action!r} (valid: {', '.join(ACTIONS)})")
        if not site:
            raise ValueError(f"fault plan entry {entry!r}: empty site")
        rules.append(FaultRule(site, action, at))
    return rules


def corrupt_largest_file(root: str) -> str:
    """Bit-flip the middle byte of the largest file under ``root`` (the
    array payload of a checkpoint step dir) and return its path."""
    largest, size = None, -1
    for dirpath, _, files in os.walk(root):
        for fn in files:
            p = os.path.join(dirpath, fn)
            s = os.path.getsize(p)
            if s > size:
                largest, size = p, s
    if largest is None or size == 0:
        raise FileNotFoundError(f"no file to corrupt under {root}")
    with open(largest, "r+b") as f:
        f.seek(size // 2)
        b = f.read(1)
        f.seek(size // 2)
        f.write(bytes([b[0] ^ 0xFF]))
    LOG.warning("fault injection: corrupted %s (byte %d flipped)",
                largest, size // 2)
    return largest


class FaultPlan:
    """A set of one-shot rules plus per-site hit counters (thread-safe)."""

    def __init__(self, rules: typing.Sequence[FaultRule] = ()):
        self.rules = list(rules)
        self._counts: typing.Dict[str, int] = {}
        self._lock = make_lock("reliability.faults.FaultPlan._lock")

    @classmethod
    def from_spec(cls, spec: typing.Optional[str]) -> "FaultPlan":
        return cls(parse_plan(spec))

    def active(self) -> bool:
        return bool(self.rules)

    def hit(self, site: str, value: typing.Optional[int] = None,
            path: typing.Optional[str] = None) -> None:
        """Record one pass through ``site`` and execute any due rule.

        ``value`` pins the trigger to an external counter (the train loop
        passes the global step so ``sigterm@step25`` survives resume);
        without it the site's own 1-based hit count is matched.  ``path``
        gives ``corrupt`` rules their target."""
        if not self.rules:
            return
        with self._lock:
            if value is None:
                value = self._counts[site] = self._counts.get(site, 0) + 1
            due = [r for r in self.rules
                   if r.site == site and not r.fired and r.at == value]
            for r in due:
                r.fired = True
        for r in due:
            self._execute(r, path)

    def take(self, site: str, value: typing.Optional[int] = None
             ) -> typing.List[str]:
        """Pop the due rules of ``site`` and return their actions WITHOUT
        executing anything — for caller-implemented actions (``nan``) where
        the site itself is the injection mechanism.  Same trigger semantics
        as :meth:`hit` (``value`` pins to an external counter; one-shot)."""
        if not self.rules:
            return []
        with self._lock:
            if value is None:
                value = self._counts[site] = self._counts.get(site, 0) + 1
            due = [r for r in self.rules
                   if r.site == site and not r.fired and r.at == value]
            for r in due:
                r.fired = True
        for r in due:
            LOG.warning("fault injection: %s taken by caller", r)
        return [r.action for r in due]

    def disarm_until(self, site: str, value: int) -> None:
        """Mark ``site`` rules with triggers <= ``value`` as already fired.

        The train loop calls this with the RESTORED step on resume: a
        config/env-driven plan is re-armed by every relaunched child, and a
        ``sigterm@stepN`` whose grace checkpoint landed exactly at step N
        would otherwise refire on the first post-resume iteration forever —
        a supervisor livelock ending in a spurious crash-loop abort."""
        with self._lock:
            for r in self.rules:
                if r.site == site and not r.fired and r.at <= value:
                    LOG.warning("fault rule %s disarmed: its trigger is "
                                "already behind the resumed position (%d)",
                                r, value)
                    r.fired = True

    def _execute(self, rule: FaultRule, path: typing.Optional[str]) -> None:
        LOG.warning("fault injection: firing %s", rule)
        if rule.action in ("nan", "drop", "stall", "wedge_healthz"):
            # caller-implemented actions reached through hit() instead of
            # take(): nothing to execute here
            LOG.error("rule %s: %r is caller-implemented (take()); "
                      "ignored at a hit() site", rule, rule.action)
            return
        if rule.action == "fail":
            raise FaultInjectedIOError(f"injected storage failure ({rule})")
        if rule.action == "die":
            raise FaultInjectedCrash(f"injected crash ({rule})")
        if rule.action in ("sigterm", "sigint"):
            sig = (signal_mod.SIGTERM if rule.action == "sigterm"
                   else signal_mod.SIGINT)
            os.kill(os.getpid(), sig)
            return
        if rule.action == "corrupt":
            if path is None:
                LOG.error("corrupt rule %s hit a site that provides no "
                          "path; ignored", rule)
                return
            corrupt_largest_file(path)


#: process-wide plan; empty (inert) until install() arms one
_PLAN = FaultPlan()


def install(spec_or_plan: typing.Union[str, FaultPlan, None] = None
            ) -> FaultPlan:
    """Arm (or clear) the process-wide plan.  ``None`` reads the
    ``HBNLP_FAULT_PLAN`` env var; an empty spec clears any previous plan —
    train() installs on every run so plans never leak across runs."""
    global _PLAN
    if spec_or_plan is None:
        spec_or_plan = os.environ.get("HBNLP_FAULT_PLAN", "")
    _PLAN = (spec_or_plan if isinstance(spec_or_plan, FaultPlan)
             else FaultPlan.from_spec(spec_or_plan))
    if _PLAN.active():
        LOG.warning("fault plan armed: %s",
                    "; ".join(str(r) for r in _PLAN.rules))
    return _PLAN


def reset() -> None:
    install("")


def active() -> bool:
    return _PLAN.active()


def hit(site: str, value: typing.Optional[int] = None,
        path: typing.Optional[str] = None) -> None:
    """Module-level convenience over the installed plan (no-op when inert)."""
    _PLAN.hit(site, value=value, path=path)


def take(site: str, value: typing.Optional[int] = None) -> typing.List[str]:
    """Module-level convenience over the installed plan ([] when inert)."""
    return _PLAN.take(site, value=value)


def disarm_until(site: str, value: int) -> None:
    _PLAN.disarm_until(site, value)
