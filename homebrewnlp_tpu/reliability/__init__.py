"""Fault tolerance: retry/backoff, fault injection, grace shutdown, budgets.

The reference was built for preemptible TPU pods — it recovers the step from
the checkpoint dir and replays the data stream from run logs — but treated
each failure as an operator problem.  This layer makes failure a first-class
input (docs/reliability.md):

- :mod:`~homebrewnlp_tpu.reliability.retry` — one backoff policy + wrapper
  for every flaky I/O call-site, with per-site obs counters.
- :mod:`~homebrewnlp_tpu.reliability.faults` — the fault-injection plan that
  proves each recovery path in CI (chaos job).
- :class:`GraceController` — SIGTERM/SIGINT handlers that drain the async
  loop and cut a grace checkpoint inside ``cfg.grace_deadline_s``, then exit
  with :data:`EXIT_PREEMPTED` so a supervisor can tell preemption from crash.
- :class:`CorruptRecordBudget` — skip-and-log for unreadable data records,
  bounded so silent data loss cannot masquerade as progress.
- ``tools/supervise.py`` consumes the exit codes to relaunch with backoff
  and abort on crash loops.
"""
from __future__ import annotations

import logging
import os
import signal
import threading
import typing

from . import faults  # noqa: F401
from .retry import (DEFAULT_POLICY, FLUSH_POLICY, RetryPolicy,  # noqa: F401
                    retry_call, retrying)
from . import dist  # noqa: F401  (after retry/faults: dist imports both)
from ..sync import make_lock, make_rlock

LOG = logging.getLogger("homebrewnlp_tpu.reliability")

#: SIGTERM/SIGINT handled: the step loop drained and a grace checkpoint was
#: cut — the supervisor relaunches immediately, no backoff (preemption is
#: not a bug)
EXIT_PREEMPTED = 83
#: the grace deadline expired (or a second signal arrived) before the grace
#: checkpoint finished — work since the last periodic checkpoint is lost
EXIT_GRACE_TIMEOUT = 84
#: the supervisor aborted: K consecutive exits with no step progress
EXIT_CRASH_LOOP = 85
#: device telemetry detected non-finite gradients under
#: ``anomaly_policy="halt"`` (docs/observability.md): the run exited before
#: cutting a potentially-poisoned final checkpoint — a supervisor treats it
#: as a crash (relaunch with backoff, resuming from the last good checkpoint)
EXIT_ANOMALY_HALT = 86
#: this host observed a DISTRIBUTED failure (peer death, coordinator loss,
#: barrier timeout — reliability/dist.py), cut a checkpoint of its own
#: healthy state, and exited: the per-host supervisors relaunch the whole
#: fleet in lockstep (docs/reliability.md "Multi-host elasticity") instead
#: of letting one host spin alone against a dead collective
EXIT_PEER_LOST = 87


class GraceController:
    """Preemption-safe shutdown: first SIGTERM/SIGINT sets ``triggered`` (the
    step loop polls it, breaks, and the normal tail cuts the final
    checkpoint); a daemon timer forces ``EXIT_GRACE_TIMEOUT`` if the drain
    exceeds ``deadline_s``, and a second signal forces it immediately.

    Handlers install only on the main thread (CPython restriction); a train
    loop hosted on a worker thread (tests, notebooks) simply never sees
    ``triggered`` and keeps today's behavior."""

    def __init__(self, deadline_s: float = 30.0,
                 exit_fn: typing.Callable[[int], None] = None):
        self.deadline_s = float(deadline_s)
        self.signame: typing.Optional[str] = None
        self._event = threading.Event()
        # reentrant: the handler runs ON the main thread between bytecodes,
        # so a signal landing inside uninstall()'s critical section would
        # self-deadlock a plain Lock
        self._lock = make_rlock("reliability.GraceController._lock")
        self._timer: typing.Optional[threading.Timer] = None
        self._prev: typing.Dict[int, typing.Any] = {}
        self._installed = False
        self._exit_fn = os._exit if exit_fn is None else exit_fn

    @property
    def triggered(self) -> bool:
        return self._event.is_set()

    def install(self) -> "GraceController":
        if threading.current_thread() is not threading.main_thread():
            LOG.info("grace signal handlers unavailable off the main thread; "
                     "SIGTERM keeps its default (immediate) behavior")
            return self
        for sig in (signal.SIGTERM, signal.SIGINT):
            self._prev[sig] = signal.signal(sig, self._handle)
        self._installed = True
        return self

    def uninstall(self) -> None:
        with self._lock:
            timer, self._timer = self._timer, None
        if timer is not None:
            timer.cancel()
        if self._installed:
            for sig, prev in self._prev.items():
                signal.signal(sig, prev)
            self._prev.clear()
            self._installed = False

    def _handle(self, signum, frame) -> None:
        if self._event.is_set():
            LOG.error("second signal during grace shutdown; exiting "
                      "immediately with code %d", EXIT_GRACE_TIMEOUT)
            self._exit_fn(EXIT_GRACE_TIMEOUT)
            return
        self.signame = signal.Signals(signum).name
        self._event.set()
        if self.deadline_s > 0:
            timer = threading.Timer(self.deadline_s, self._expire)
            timer.daemon = True
            with self._lock:
                self._timer = timer
            timer.start()
        LOG.warning("%s received: draining the step loop and cutting a "
                    "grace checkpoint (deadline %.0fs)", self.signame,
                    self.deadline_s)

    def _expire(self) -> None:
        LOG.error("grace deadline (%.0fs) exceeded before the grace "
                  "checkpoint finished; forcing exit %d", self.deadline_s,
                  EXIT_GRACE_TIMEOUT)
        self._exit_fn(EXIT_GRACE_TIMEOUT)


class CorruptRecordBudget:
    """Bounded skip-and-log for unreadable records/shards.

    Each ``spend`` logs the skip and increments
    ``hbnlp_corrupt_records_total``; crossing ``limit`` re-raises — a
    corrupt *fleet* of shards is a data problem the run must surface, not
    paper over.  Shared across one pipeline's files (thread-safe: the
    prefetcher thread reads through it)."""

    def __init__(self, limit: int, registry=None, pipeline: str = "text"):
        from ..obs.registry import REGISTRY
        self.limit = int(limit)
        self.spent = 0
        self._lock = make_lock("reliability.CorruptRecordBudget._lock")
        reg = REGISTRY if registry is None else registry
        # labelled by pipeline so dashboards can tell a rotting text corpus
        # from a rotting frame store (the video decoder spends the budget on
        # undecodable JPEGs, data/video.py)
        self._counter = reg.counter(
            "hbnlp_corrupt_records_total",
            "unreadable data records/shards skipped under the corrupt "
            "budget", labelnames=("pipeline",)).labels(pipeline=pipeline)

    def spend(self, what: str, exc: BaseException) -> None:
        """Account one unreadable record/shard; raises when over budget."""
        with self._lock:
            self.spent += 1
            spent = self.spent
        self._counter.inc()
        if spent > self.limit:
            LOG.error("corrupt-record budget exhausted (%d > %d) at %s: %r",
                      spent, self.limit, what, exc)
            raise OSError(
                f"corrupt-record budget exhausted ({spent} > {self.limit}) "
                f"reading {what}") from exc
        LOG.warning("skipping unreadable data in %s (%r) — corrupt-record "
                    "budget %d/%d", what, exc, spent, self.limit)
