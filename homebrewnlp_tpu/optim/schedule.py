"""Learning-rate schedules.

Reproduces the reference's composable schedule modules
(/root/reference/src/optimizer/learning_rate.py:28-72): each named module in
``learning_rate_config`` transforms the running LR in order.  Host-side tf ops
there become a pure jnp function of the step here — it traces into the train
step so the schedule lives on-device.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..config import Config


def _linear_warmup(lr, step, c):
    final = jnp.float32(c.final_step)
    warm = step / jnp.maximum(final, 1.0)
    return lr * jnp.where(step < final, warm, 1.0)


def _exponential_decay(lr, step, c):
    exp = jnp.maximum(step - c.start_step, 0.0)
    return lr * jnp.power(jnp.float32(c.factor), exp)


def _linear_decay(lr, step, c):
    span = jnp.maximum(jnp.float32(c.final_step - c.start_step), 1.0)
    decay = 1.0 - (step - c.start_step) / span
    return lr * jnp.clip(decay, 0.0, 1.0)


def _lower_bound(lr, step, c):
    return jnp.maximum(lr, jnp.float32(c.factor))


def _upper_bound(lr, step, c):
    return jnp.minimum(lr, jnp.float32(c.factor))


MODULES = {
    "linear_warmup": _linear_warmup,
    "exponential_decay": _exponential_decay,
    "linear_decay": _linear_decay,
    "lower_bound": _lower_bound,
    "upper_bound": _upper_bound,
}


def learning_rate(cfg: Config, step: jnp.ndarray) -> jnp.ndarray:
    """Scheduled LR as a scalar f32 traced from the (f32-cast) global step."""
    lr = jnp.float32(cfg.learning_rate)
    stepf = step.astype(jnp.float32)
    for name, conf in cfg.learning_rate_config.items():
        if name not in MODULES:
            raise ValueError(f"unknown LR schedule module {name!r}")
        lr = MODULES[name](lr, stepf, conf)
    return lr
