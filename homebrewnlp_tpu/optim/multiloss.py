"""Multi-loss gradient combination: PCGrad and MGDA.

The reference interleaves these into its hand-rolled backprop walk, gated to
variables whose name contains 'body' (/root/reference/src/optimizer/
gradients.py:11-66).  Here each loss is differentiated separately with
``jax.grad`` and the per-variable gradients are combined functionally; the
same 'body' gating applies.
"""
from __future__ import annotations

import typing

import jax.numpy as jnp

Grads = typing.Dict[str, jnp.ndarray]


def _is_body(name: str) -> bool:
    return "body" in name


def pcgrad(grads_per_loss: typing.Sequence[Grads]) -> Grads:
    """Project conflicting gradients: for each body variable, remove from each
    loss-gradient its negative component along every *other* loss-gradient,
    then sum (PCGrad, Yu et al. 2020).

    Deliberate divergence from the reference (gradients.py:22-35): its
    rotating in-place variant multiplies by ||g||^2 where the projection
    requires dividing, and reads a stale loop variable for later norms; we
    use the paper's formula (projections against the original gradients)."""
    first = grads_per_loss[0]
    if len(grads_per_loss) == 1:
        return dict(first)
    out: Grads = {}
    for name in first:
        gs = [g[name].astype(jnp.float32) for g in grads_per_loss]
        if not _is_body(name):
            out[name] = sum(gs[1:], gs[0])
            continue
        sq = [1e-8 + jnp.sum(g * g) for g in gs]
        projected = []
        for i, g in enumerate(gs):
            pg = g
            for j, (gj, sqj) in enumerate(zip(gs, sq)):
                if j != i:
                    pg = pg - gj * (jnp.minimum(jnp.sum(pg * gj), 0) / sqj)
            projected.append(pg)
        out[name] = sum(projected[1:], projected[0])
    return out


def mgda_gamma(grads_per_loss: typing.Sequence[Grads]) -> jnp.ndarray:
    """Closed-form min-norm point for the 2-loss case (reference
    __init__.py:110-126): gamma in [min_gamma, 1-min_gamma] weighting loss 1."""
    assert len(grads_per_loss) == 2, "MGDA supports exactly two losses"
    g1, g2 = grads_per_loss
    zero = jnp.float32(0)
    v11 = sum((jnp.sum(jnp.square(g1[k].astype(jnp.float32)))
               for k in g1 if _is_body(k)), zero)
    v12 = sum((jnp.sum(g1[k].astype(jnp.float32) * g2[k].astype(jnp.float32))
               for k in g1 if _is_body(k)), zero)
    v22 = sum((jnp.sum(jnp.square(g2[k].astype(jnp.float32)))
               for k in g2 if _is_body(k)), zero)
    min_gamma = 0.001
    gamma = (1 - min_gamma) * (v12 >= v11).astype(jnp.float32)
    gamma = gamma + min_gamma * (v12 >= v22).astype(jnp.float32) * (gamma == 0)
    # min-norm interior point (v22-v12)/||g1-g2||^2; the epsilon guards the
    # g1==g2 degenerate case (the branch above already handles it, but the
    # term is evaluated unconditionally).  The reference's denominator
    # (v11+v22+2*v12, __init__.py:123) has a sign error; we use the correct
    # min-norm form — documented divergence.
    denom = jnp.maximum(v11 + v22 - 2 * v12, 1e-8)
    gamma = gamma + (gamma == 0) * (v22 - v12) / denom
    return gamma


def mgda(grads_per_loss: typing.Sequence[Grads]) -> Grads:
    gamma = mgda_gamma(grads_per_loss)
    g1, g2 = grads_per_loss
    return {k: (g1[k].astype(jnp.float32) * gamma
                + g2[k].astype(jnp.float32) * (1 - gamma))
            for k in g1}


def linear(grads_per_loss: typing.Sequence[Grads]) -> Grads:
    first = grads_per_loss[0]
    return {k: sum((g[k] for g in grads_per_loss[1:]), first[k]) for k in first}


STRATEGIES = {"linear": linear, "pcgrad": pcgrad, "mgda": mgda}
