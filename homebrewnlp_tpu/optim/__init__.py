"""Optimizer layer: DSL-chained per-variable transforms + schedules + decay.

The reference's ``get_optimizer`` (/root/reference/src/optimizer/__init__.py:
69-186) hand-walks the mtf graph in reverse and emits assign ops; in JAX the
backward pass is ``jax.grad`` and this module is a pure function
``(params, grads, state, step) -> (new_params, new_state, lr)`` compiled into
the train step.  Reproduced semantics:

- optimizer string-DSL chain (``"adaptive_clip:0.003-sm3-momentum:0.9:1:1-
  learning_rate"``) — see transforms.py
- rezero LR multiplier (reference __init__.py:46-47)
- selective weight decay on "large" tensors via the name/shape heuristic
  (reference __init__.py:49-61), applied *after* the chain so it is not
  adaptively normalized, scaled by lr * weight_decay
- optimizer state in ``optimizer_slice_dtype``, math in
  ``optimizer_calculation_dtype`` (reference dataclass.py:200-204)
- final update: ``w -= transformed_grad`` (reference __init__.py:63-66)
"""
from __future__ import annotations

import typing

import jax
import jax.numpy as jnp

from ..config import PIPE_STAGE, Config
from ..ops.init import feature_dims_used
from .multiloss import STRATEGIES
from .schedule import learning_rate as learning_rate_fn
from .transforms import (VarCtx, apply_chain, chain_slot_shapes, masked,
                         parse_chain, update_sumsq)

Params = typing.Dict[str, jnp.ndarray]
OptState = typing.Dict[str, typing.Dict[str, jnp.ndarray]]


def is_large_tensor(name: str, axis_names: typing.Sequence[str],
                    size: int, cfg: Config) -> bool:
    """Weight-decay eligibility heuristic (reference __init__.py:52-61)."""
    features_used = feature_dims_used(axis_names, cfg.feature_dims)
    ndims = len(axis_names)
    large = (features_used and ndims > len(cfg.feature_dims)) or (
        not features_used and ndims >= 2)
    large &= size > 1
    large &= "norm" not in name
    large &= "rezero" not in name
    large &= "embed" not in name
    large &= "input" not in name or "lang_in" in name or "vid_in" in name
    large &= "output" not in name or "lang_out" in name or "vid_out" in name
    return large


def _parse_global_clip(spec: str) -> float:
    for name, args in parse_chain(spec):
        if name == "global_l2norm_clip":
            return float(args[0])
    raise ValueError("global_l2norm_clip not in spec")


class Optimizer:
    """DSL-chain optimizer over a flat param dict.

    ``axes`` maps param name -> axis-name tuple (from ``init_params``) and
    drives both the decay heuristic and state sharding specs."""

    def __init__(self, cfg: Config, axes: typing.Dict[str, typing.Tuple[str, ...]]):
        self.cfg = cfg
        self.axes = dict(axes)
        self.spec = cfg.optimizer

    # -- state ---------------------------------------------------------------
    def _is_stacked(self, name: str) -> bool:
        """Stage-stacked pipeline-parallel variable (models.
        stack_pipeline_params): leading [P] axis over the pipeline mesh axis.
        The DSL chain runs per STAGE (vmapped over the leading axis) so
        per-tensor reductions — novograd/sm3 moments, AGC and l2 clip norms,
        centralisation means, graft magnitudes, weight standardisation —
        keep the exact semantics of the unstacked per-depth layout."""
        ax = self.axes.get(name, ())
        return len(ax) > 0 and ax[0] == PIPE_STAGE

    def init(self, params: Params) -> OptState:
        dtype = self.cfg.optimizer_slice_dtype
        state: OptState = {}
        for name, value in params.items():
            if self._is_stacked(name):
                shapes = chain_slot_shapes(self.spec, value.shape[1:])
                state[name] = {k: jnp.zeros((value.shape[0],) + s, dtype)
                               for k, s in shapes.items()}
            else:
                shapes = chain_slot_shapes(self.spec, value.shape)
                state[name] = {k: jnp.zeros(s, dtype) for k, s in shapes.items()}
        return state

    def slot_axis_names(self) -> typing.Dict[str, typing.Dict[str, typing.Tuple[str, ...]]]:
        """Axis names for every slot (for sharding): full-shape slots inherit
        the variable's axes; per-dim sm3 buffers keep that one axis; scalar
        slots get ().  Stage-stacked variables' slots all carry the leading
        PIPE_STAGE axis (they are vmapped per stage)."""
        out: typing.Dict[str, typing.Dict[str, typing.Tuple[str, ...]]] = {}
        for name, axis_names in self.axes.items():
            stacked = self._is_stacked(name)
            per_stage = axis_names[1:] if stacked else axis_names
            shapes = chain_slot_shapes(self.spec, [1] * len(per_stage))
            slot_axes = {}
            for k, shape in shapes.items():
                leaf = k.rsplit("/", 1)[-1]
                if leaf.startswith("dim") and leaf[3:].isdigit():
                    ax = (per_stage[int(leaf[3:])],)
                elif len(shape) == len(per_stage):
                    ax = tuple(per_stage)
                else:
                    ax = tuple(per_stage[:len(shape)])
                slot_axes[k] = ((PIPE_STAGE,) + ax) if stacked else ax
            out[name] = slot_axes
        return out

    # -- update --------------------------------------------------------------
    def update(self, params: Params, grads: Params, state: OptState,
               step: jnp.ndarray, *,
               skip: typing.Optional[jnp.ndarray] = None,
               collect_update_sq: bool = False):
        """One optimizer application.  ``step`` is the 0-indexed global update
        counter; debiasing uses step+1.  Returns ``(new_params, new_state,
        lr)``, plus a ``{name: squared-update-L2}`` dict when
        ``collect_update_sq`` (the device-telemetry update-norm tap).

        ``skip`` (traced scalar bool, the skip_step anomaly policy): when
        true, params AND slots are masked back to their incoming values in
        their ORIGINAL dtypes — the whole update is a bit-exact no-op, so a
        NaN gradient can neither move a weight nor poison a moment slot."""
        cfg = self.cfg
        cdtype = cfg.optimizer_calculation_dtype
        lr = learning_rate_fn(cfg, step)
        step_count = (step + 1).astype(jnp.float32)

        global_norm_recip = None
        if "global_l2norm_clip" in self.spec:
            clip = _parse_global_clip(self.spec)
            gsum = sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                       for g in grads.values())
            global_norm_recip = jnp.reciprocal(
                jnp.sqrt(jnp.maximum(gsum, clip ** -2))).astype(cdtype)

        new_params: Params = {}
        new_state: OptState = {}
        update_sq: typing.Dict[str, jnp.ndarray] = {}
        for name, value in params.items():
            stacked = self._is_stacked(name)
            axis_names = self.axes.get(name, ())
            per_stage_axes = axis_names[1:] if stacked else axis_names
            size = int(value.size) // (value.shape[0] if stacked else 1)
            large = is_large_tensor(name, per_stage_axes, size, cfg)
            rezero = "rezero" in name

            def one(value, grad, raw_slots):
                """Per-(stage-)tensor chain + decay + standardisation, so
                per-tensor reductions see one stage's weights at a time."""
                grad = grad.astype(cdtype)
                val = value.astype(cdtype)
                ctx = VarCtx(grad=grad, value=val, lr=lr,
                             beta1=cfg.opt_beta1, beta2=cfg.opt_beta2,
                             step_count=step_count,
                             global_norm_reciprocal=global_norm_recip)
                slots = {k: v.astype(cdtype) for k, v in raw_slots.items()}
                out, slots = apply_chain(self.spec, ctx, slots)
                if rezero:
                    out = out * cfg.rezero_lr_multiplier
                if cfg.weight_decay > 0 and large:
                    out = out + val * (lr.astype(cdtype) * cfg.weight_decay)
                new = val - out
                if cfg.weight_standardisation and large:
                    # standardize large weights after each update: remove the
                    # mean and restore the pre-centering norm, keeping the
                    # weight on the same sphere while preventing mean drift.
                    # The reference declares this knob (dataclass.py:49) and
                    # its implication of weight_centralisation
                    # (dataclass.py:218) but never consumes it; honored here.
                    centered = new - jnp.mean(new)
                    norm = jnp.sqrt(jnp.sum(jnp.square(new)))
                    cnorm = jnp.sqrt(jnp.maximum(
                        jnp.sum(jnp.square(centered)),
                        jnp.asarray(1e-12, cdtype)))
                    new = centered * (norm / cnorm)
                new_value = new.astype(value.dtype)
                new_slots = {k: v.astype(cfg.optimizer_slice_dtype)
                             for k, v in slots.items()}
                if skip is not None:
                    new_value = masked(skip, value, new_value)
                    new_slots = {k: masked(skip, raw_slots[k], v)
                                 for k, v in new_slots.items()}
                if not collect_update_sq:
                    return new_value, new_slots
                return new_value, new_slots, update_sumsq(value, new_value)

            fn = jax.vmap(one) if stacked else one
            result = fn(value, grads[name], state[name])
            new_params[name], new_state[name] = result[0], result[1]
            if collect_update_sq:
                # stacked pipeline variables return a per-stage [P] vector
                update_sq[name] = jnp.sum(result[2])
        if collect_update_sq:
            return new_params, new_state, lr, update_sq
        return new_params, new_state, lr

    # -- multi-loss ----------------------------------------------------------
    def combine_losses(self, grads_per_loss: typing.Sequence[Params]) -> Params:
        return STRATEGIES[self.cfg.multi_loss_strategy](list(grads_per_loss))


__all__ = ["Optimizer", "is_large_tensor", "learning_rate_fn", "STRATEGIES"]
