"""Per-variable optimizer transforms behind the optimizer string-DSL.

Matches the numerics of the reference DSL entries
(/root/reference/src/optimizer/optimizers.py): ``adam``, ``novograd``, ``sm3``,
``adaptive_clip`` (AGC), ``l2norm_clip``, ``global_l2norm_clip``,
``value_clip``, ``gradient_centralisation``, ``weight_centralisation``,
``learning_rate``, ``momentum`` (incl. nesterov) and ``graft``.  The reference
threads a mutable OptimizerCtx through mtf assign ops; here each transform is a
pure function ``(ctx, slots, *args) -> (new_grad, new_slots)`` over jnp arrays,
chained functionally — the whole update compiles into the train step.

Slot layout is declared separately (``slot_shapes``) so the optimizer state
pytree can be initialized (and sharded) ahead of time.
"""
from __future__ import annotations

import dataclasses
import typing

import jax.numpy as jnp

Slots = typing.Dict[str, jnp.ndarray]


@dataclasses.dataclass
class VarCtx:
    """Per-variable context: gradient being transformed plus hyperparams."""
    grad: jnp.ndarray          # in optimizer_calculation_dtype
    value: jnp.ndarray         # current weight, optimizer_calculation_dtype
    lr: jnp.ndarray            # scheduled learning rate (scalar)
    beta1: float
    beta2: float
    step_count: jnp.ndarray    # 1-indexed update count, for debiasing
    global_norm_reciprocal: typing.Optional[jnp.ndarray]  # set by the driver


def _opt_rsqrt(x: jnp.ndarray) -> jnp.ndarray:
    # reciprocal(max(sqrt(x), 1e-5)) — reference optimizers.py:14-15
    return jnp.reciprocal(jnp.maximum(jnp.sqrt(x), 1e-5))


def _debias_factor(beta: float, step_count: jnp.ndarray) -> jnp.ndarray:
    return jnp.reciprocal(1.0 - jnp.power(jnp.float32(beta), step_count))


def _sumsq(x: jnp.ndarray) -> jnp.ndarray:
    return jnp.sum(jnp.square(x))


def all_finite(x: jnp.ndarray) -> jnp.ndarray:
    """Scalar bool: every entry finite (the NaN/Inf sentinel primitive the
    device-telemetry layer reduces over the whole gradient tree)."""
    return jnp.isfinite(x).all()


def masked(skip: jnp.ndarray, old: jnp.ndarray, new: jnp.ndarray
           ) -> jnp.ndarray:
    """``where(skip, old, new)`` in ``old``'s dtype — the skip_step anomaly
    policy's update mask.  Applied to params AND slots in the ORIGINAL
    storage dtype (never the calculation dtype): a skipped step must be a
    bit-exact no-op, and a f32->bf16->f32 round-trip would silently perturb
    the very state the skip is protecting."""
    return jnp.where(skip, old, new.astype(old.dtype))


def update_sumsq(old_value: jnp.ndarray, new_value: jnp.ndarray
                 ) -> jnp.ndarray:
    """Squared L2 of the APPLIED update (f32 accumulate), measured on the
    stored values so it reflects exactly what changed — zero for a masked
    skip_step update, standardisation/decay/rezero effects included."""
    diff = old_value.astype(jnp.float32) - new_value.astype(jnp.float32)
    return jnp.sum(jnp.square(diff))


# -- stateful optimizers -----------------------------------------------------

def adam_slots(shape: typing.Sequence[int]) -> typing.Dict[str, tuple]:
    return {"exp_avg_p1": tuple(shape), "exp_avg_p2": tuple(shape)}


def adam(ctx: VarCtx, slots: Slots) -> typing.Tuple[jnp.ndarray, Slots]:
    p2 = slots["exp_avg_p2"] * ctx.beta2 + jnp.square(ctx.grad) * (1 - ctx.beta2)
    p1 = slots["exp_avg_p1"] * ctx.beta1 + ctx.grad * (1 - ctx.beta1)
    out = (_opt_rsqrt(p2 * _debias_factor(ctx.beta2, ctx.step_count)) * p1
           * _debias_factor(ctx.beta1, ctx.step_count))
    return out, {"exp_avg_p1": p1, "exp_avg_p2": p2}


def novograd_slots(shape: typing.Sequence[int]) -> typing.Dict[str, tuple]:
    if len(shape) == 0:
        return adam_slots(shape)
    return {"exp_avg_p1": tuple(shape), "exp_avg_p2": ()}


def novograd(ctx: VarCtx, slots: Slots) -> typing.Tuple[jnp.ndarray, Slots]:
    if ctx.grad.ndim == 0:  # scalars fall back to adam (reference :46-47)
        return adam(ctx, slots)
    p2_old = slots["exp_avg_p2"]
    # p1 uses the *previous* second moment; p2 then updates; the returned
    # update debiases the *new* p2 (reference optimizers.py:49-57).
    p1 = ctx.beta1 * slots["exp_avg_p1"] + ctx.grad * _opt_rsqrt(p2_old)
    p2 = p2_old * ctx.beta2 + _sumsq(ctx.grad) * (1 - ctx.beta2)
    out = ctx.beta1 * p1 + ctx.grad * _opt_rsqrt(
        p2 * _debias_factor(ctx.beta2, ctx.step_count))
    return out, {"exp_avg_p1": p1, "exp_avg_p2": p2}


def sm3_slots(shape: typing.Sequence[int]) -> typing.Dict[str, tuple]:
    if len(shape) == 0:
        return adam_slots(shape)
    return {f"dim{i}": (s,) for i, s in enumerate(shape)}


def sm3(ctx: VarCtx, slots: Slots) -> typing.Tuple[jnp.ndarray, Slots]:
    """SM3: rank-1 factored second moment — per-axis max buffers whose
    broadcast min approximates the full accumulator (reference :60-76)."""
    if ctx.grad.ndim == 0:
        return adam(ctx, slots)
    ndim = ctx.grad.ndim

    def _expand(buf: jnp.ndarray, axis: int) -> jnp.ndarray:
        shape = [1] * ndim
        shape[axis] = buf.shape[0]
        return buf.reshape(shape)

    acc = _expand(slots["dim0"], 0)
    for i in range(1, ndim):
        acc = jnp.minimum(acc, _expand(slots[f"dim{i}"], i))
    acc = acc + jnp.square(ctx.grad)
    new_slots = {
        f"dim{i}": jnp.max(acc, axis=tuple(a for a in range(ndim) if a != i))
        for i in range(ndim)}
    return ctx.grad * _opt_rsqrt(acc), new_slots


def momentum_slots(shape: typing.Sequence[int]) -> typing.Dict[str, tuple]:
    return {"momentum": tuple(shape)}


def momentum(ctx: VarCtx, slots: Slots, momentum_multiplier: str = "0.9",
             gradient_multiplier: str = "1", nesterov: str = "0"
             ) -> typing.Tuple[jnp.ndarray, Slots]:
    mul = float(momentum_multiplier)
    gmul = float(gradient_multiplier)
    state = mul * slots["momentum"] + ctx.grad * gmul
    out = ctx.grad + mul * state if bool(int(nesterov)) else state
    return out, {"momentum": state}


# -- stateless transforms ----------------------------------------------------

def adaptive_clip(ctx: VarCtx, slots: Slots, clip: str
                  ) -> typing.Tuple[jnp.ndarray, Slots]:
    """AGC: scale the gradient so ||g|| <= clip * ||w|| (reference :79-84)."""
    c = float(clip)
    grd_norm_recip = jnp.minimum(jnp.reciprocal(jnp.sqrt(_sumsq(ctx.grad))), 1e6)
    wgt_norm = jnp.maximum(jnp.sqrt(_sumsq(ctx.value)), 1e-3)
    return ctx.grad * jnp.minimum(wgt_norm * grd_norm_recip * c, 1.0), slots


def l2norm_clip(ctx: VarCtx, slots: Slots, clip: str
                ) -> typing.Tuple[jnp.ndarray, Slots]:
    c = float(clip)
    scale = c * jnp.reciprocal(jnp.sqrt(jnp.maximum(_sumsq(ctx.grad), c ** -2)))
    return ctx.grad * scale, slots


def global_l2norm_clip(ctx: VarCtx, slots: Slots, clip: str
                       ) -> typing.Tuple[jnp.ndarray, Slots]:
    c = float(clip)
    assert ctx.global_norm_reciprocal is not None
    return ctx.grad * (c * ctx.global_norm_reciprocal), slots


def value_clip(ctx: VarCtx, slots: Slots, clip: str
               ) -> typing.Tuple[jnp.ndarray, Slots]:
    c = float(clip)
    return jnp.clip(ctx.grad, -c, c), slots


def gradient_centralisation(ctx: VarCtx, slots: Slots
                            ) -> typing.Tuple[jnp.ndarray, Slots]:
    return ctx.grad - jnp.mean(ctx.grad), slots


def weight_centralisation(ctx: VarCtx, slots: Slots
                          ) -> typing.Tuple[jnp.ndarray, Slots]:
    return ctx.grad + jnp.mean(ctx.value), slots


def multiply_learning_rate(ctx: VarCtx, slots: Slots
                           ) -> typing.Tuple[jnp.ndarray, Slots]:
    return ctx.grad * ctx.lr.astype(ctx.grad.dtype), slots


TRANSFORMS: typing.Dict[str, typing.Callable] = {
    "adam": adam,
    "novograd": novograd,
    "sm3": sm3,
    "momentum": momentum,
    "adaptive_clip": adaptive_clip,
    "l2norm_clip": l2norm_clip,
    "global_l2norm_clip": global_l2norm_clip,
    "value_clip": value_clip,
    "gradient_centralisation": gradient_centralisation,
    "weight_centralisation": weight_centralisation,
    "learning_rate": multiply_learning_rate,
}

SLOT_FNS: typing.Dict[str, typing.Callable] = {
    "adam": adam_slots,
    "novograd": novograd_slots,
    "sm3": sm3_slots,
    "momentum": momentum_slots,
}


def graft(ctx: VarCtx, slots: Slots, inner: str, *args: str
          ) -> typing.Tuple[jnp.ndarray, Slots]:
    """Norm-graft: direction of the incoming gradient, magnitude of the inner
    optimizer's step (reference optimizers.py:145-151)."""
    inner_out, new_slots = TRANSFORMS[inner](ctx, slots, *args)
    scale = (jnp.reciprocal(jnp.sqrt(_sumsq(ctx.grad)))
             * jnp.sqrt(_sumsq(inner_out)))
    return ctx.grad * scale, new_slots


def graft_slots(shape: typing.Sequence[int], inner: str, *args: str
                ) -> typing.Dict[str, tuple]:
    return SLOT_FNS.get(inner, lambda s: {})(shape)


TRANSFORMS["graft"] = graft


def parse_chain(spec: str) -> typing.List[typing.Tuple[str, typing.Tuple[str, ...]]]:
    """``"adaptive_clip:0.003-sm3-momentum:0.9:1:1-learning_rate"`` ->
    [(name, args), ...] (reference __init__.py:42-44)."""
    out = []
    for part in spec.split("-"):
        name, *args = part.split(":")
        if name not in TRANSFORMS:
            raise ValueError(f"unknown optimizer DSL entry {name!r}; "
                             f"known: {sorted(TRANSFORMS)}")
        out.append((name, tuple(args)))
    return out


def chain_slot_shapes(spec: str, shape: typing.Sequence[int]
                      ) -> typing.Dict[str, tuple]:
    """Slot name -> shape for one variable under the full DSL chain.  Slot
    names are prefixed by chain position so repeated entries don't collide."""
    shapes: typing.Dict[str, tuple] = {}
    for i, (name, args) in enumerate(parse_chain(spec)):
        if name == "graft":
            sub = graft_slots(shape, *args)
        elif name in SLOT_FNS:
            sub = SLOT_FNS[name](shape)
        else:
            continue
        for k, v in sub.items():
            shapes[f"{i}/{name}/{k}"] = v
    return shapes


def apply_chain(spec: str, ctx: VarCtx, slots: Slots
                ) -> typing.Tuple[jnp.ndarray, Slots]:
    """Run the DSL chain over one variable's gradient."""
    new_slots: Slots = {}
    for i, (name, args) in enumerate(parse_chain(spec)):
        prefix = f"{i}/{name}/"
        sub = {k[len(prefix):]: v for k, v in slots.items()
               if k.startswith(prefix)}
        ctx.grad, sub = TRANSFORMS[name](ctx, sub, *args)
        for k, v in sub.items():
            new_slots[prefix + k] = v
    for k, v in slots.items():
        if k not in new_slots:
            new_slots[k] = v
    return ctx.grad, new_slots
