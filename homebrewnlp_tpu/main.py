"""CLI entry: run modes over a JSON config.

Mirrors the reference CLI (/root/reference/main.py:12-30, src/main.py:36-166):
``--model cfg.json --run_mode {train,sample,query,web_api,debug}``.  TPU
bootstrap collapses from cluster-resolver/session plumbing to
``jax.distributed.initialize`` (multi-host) + mesh construction; run-config
and model-size artifacts are dumped next to checkpoints exactly like the
reference (src/main.py:66-69, src/run/utils_run.py:108-113).
"""
from __future__ import annotations

import argparse
import json
import os
import time
import typing

import numpy as np


def parse_args(argv: typing.Optional[typing.Sequence[str]] = None):
    p = argparse.ArgumentParser()
    p.add_argument("--model", type=str, required=True, help="JSON config path")
    p.add_argument("--tpu", type=str, default="", help="unused on single host;"
                   " 'host:port,rank,size' triggers jax.distributed.initialize")
    p.add_argument("--run_mode", type=str, default="train",
                   choices=["train", "sample", "query", "web_api", "debug"])
    p.add_argument("--steps", type=int, default=0,
                   help="override train_steps (0 = config value)")
    p.add_argument("--workers", type=int, default=1)
    p.add_argument("--debug_grad", action="store_true")
    p.add_argument("--port", type=int, default=8000)
    return p.parse_args(argv)


def _init_distributed(tpu_arg: str) -> None:
    if "," in tpu_arg:
        import jax
        addr, rank, size = tpu_arg.split(",")
        jax.distributed.initialize(addr, int(size), int(rank))


def _build_state(cfg, batch, mesh=None):
    from .train import Checkpointer, Trainer, color_print
    trainer = Trainer(cfg, mesh)
    state = trainer.init(batch)
    ckpt = None
    data_state = None
    if cfg.use_checkpointing:
        ckpt = Checkpointer(os.path.join(cfg.model_path, "ckpt"),
                            cfg.max_checkpoints_keep)
        state, data_state = ckpt.restore(state)
        color_print(f"restored step {int(state.step)} from checkpoints"
                    if int(state.step) else "fresh initialization")
    return trainer, state, ckpt, data_state


def _dump_run_artifacts(cfg, trainer, params) -> None:
    os.makedirs(cfg.model_path, exist_ok=True)
    with open(os.path.join(cfg.model_path, "run_config.json"), "w") as f:
        json.dump({k: str(v) for k, v in cfg.dict().items()}, f, indent=2)
    census = trainer.param_census(params)
    with open(os.path.join(cfg.model_path, "model_size.info"), "w") as f:
        json.dump(census, f, indent=2)


def train(cfg, args) -> None:
    import jax
    from .data import RunLog, dataset, to_global
    from .data.synthetic import synthetic_text_batch
    from .train import MetricWriter, color_print

    have_data = bool(cfg.dataset_configs) and any(
        __import__("glob").glob(d["path"]) for d in cfg.dataset_configs)
    slice_index = jax.process_index()
    slice_count = max(1, jax.process_count())
    local_batch = cfg.train_batch_size // slice_count

    if have_data:
        pipe = dataset(cfg, local_batch, slice_index, slice_count)
        batches = iter(pipe)
        first_np = next(batches)
    else:
        color_print("no dataset files found; using synthetic data")
        pipe = None
        first_np = synthetic_text_batch(cfg, 0)

    from .parallel import make_mesh
    mesh = make_mesh(cfg)
    trainer, state, ckpt, data_state = _build_state(
        cfg, to_global(first_np, cfg, mesh), mesh)
    step0 = int(state.step)
    if pipe is not None and data_state and "pipeline" in data_state:
        # resume the cursor on a *fresh* pipeline, then draw the first batch
        # from the restored position (first_np above came from the start of
        # the stream and was only used as the init template)
        pipe = dataset(cfg, local_batch, slice_index, slice_count)
        pipe.load_state_dict(data_state["pipeline"])
        batches = iter(pipe)
        first_np = next(batches)
    elif pipe is None and step0:
        first_np = synthetic_text_batch(cfg, step0)

    _dump_run_artifacts(cfg, trainer, state.params)
    writer = MetricWriter(cfg.model_path)
    run_log = RunLog(cfg.model_path)
    steps = args.steps or cfg.train_steps
    rng = jax.random.key(cfg.data_seed)
    t0 = time.time()
    np_batch = first_np
    for i in range(step0, steps):
        gb = to_global(np_batch, cfg, trainer.mesh)
        state, metrics = trainer.step(state, gb, jax.random.fold_in(rng, i))
        writer.write(i, metrics)
        if (i + 1) % 10 == 0:
            rate = (i + 1 - step0) / (time.time() - t0)
            color_print(f"step {i + 1} loss {float(metrics['loss']):.4f} "
                        f"({rate:.2f} steps/s)")
        if ckpt is not None and (i + 1) % cfg.steps_per_checkpoint == 0:
            data_state = ({"pipeline": pipe.state_dict()} if pipe is not None
                          else None)
            ckpt.save(state, data_state)
        if pipe is not None:
            np_batch = next(batches)
        else:
            np_batch = synthetic_text_batch(cfg, i + 1)
    if ckpt is not None:
        ckpt.save(state, {"pipeline": pipe.state_dict()} if pipe else None)
        ckpt.wait()
    run_log.append(steps=steps - step0, batch_size=cfg.train_batch_size,
                   slice_count=slice_count, ctx=cfg.sequence_length,
                   grad_accumulation=cfg.grad_accumulation,
                   interleave_size=cfg.interleaved_datasets,
                   token_patch_size=cfg.token_patch_size)
    run_log.save()
    writer.close()


def _params_for_serving(cfg):
    from .utils import random_text_batch
    batch = random_text_batch(cfg)
    if cfg.use_checkpointing:
        from .train import Checkpointer, Trainer
        state = Trainer(cfg).init(batch)
        state, _ = Checkpointer(os.path.join(cfg.model_path, "ckpt")).restore(state)
        return state.params
    from .models import init_params
    params, _ = init_params(cfg, batch)
    return params


def sample(cfg, args) -> None:
    from .serve import CompletionEngine, render_text_samples
    params = _params_for_serving(cfg)
    engine = CompletionEngine(cfg, params)
    for i in range(cfg.num_of_sample):
        out = engine.complete_tokens([int(cfg.concat_token)])
        render_text_samples(out[None], engine.tokenizer)


def query(cfg, args) -> None:
    from .serve import repl
    repl(cfg, _params_for_serving(cfg))


def web_api(cfg, args) -> None:
    from .serve import serve as rest_serve
    print(f"serving on :{args.port}")
    rest_serve(cfg, _params_for_serving(cfg), port=args.port)


def debug(cfg, args) -> None:
    """Self-similarity nondeterminism check (reference interface.py:283-302)."""
    from .serve import CompletionEngine, similarity_score
    params = _params_for_serving(cfg)
    engine = CompletionEngine(cfg, params)
    prompt = list(range(min(16, cfg.vocab_size)))
    samples = [engine.complete_tokens(prompt, temperature=0.0)
               for _ in range(max(2, min(4, cfg.equal_debugging_items_per_check)))]
    score = similarity_score([np.asarray(s) for s in samples])
    print(f"similarity: {score * 100:.2f}%")
    if score < 1.0:
        raise SystemExit("nondeterministic sampling detected")


RUN_MODE_FNS = {"train": train, "sample": sample, "query": query,
                "web_api": web_api, "debug": debug}


def main(argv: typing.Optional[typing.Sequence[str]] = None) -> None:
    args = parse_args(argv)
    _init_distributed(args.tpu)
    from .config import Config
    cfg = Config.from_json(args.model)
    if args.debug_grad:
        cfg.debug_gradients = True
    RUN_MODE_FNS[args.run_mode](cfg, args)
