"""CLI entry: run modes over a JSON config.

Mirrors the reference CLI (/root/reference/main.py:12-30, src/main.py:36-166):
``--model cfg.json --run_mode {train,sample,query,web_api,debug}``.  TPU
bootstrap collapses from cluster-resolver/session plumbing to
``jax.distributed.initialize`` (multi-host) + mesh construction; run-config
and model-size artifacts are dumped next to checkpoints exactly like the
reference (src/main.py:66-69, src/run/utils_run.py:108-113).
"""
from __future__ import annotations

import argparse
import json
import os
import time
import typing

import numpy as np


def parse_args(argv: typing.Optional[typing.Sequence[str]] = None):
    p = argparse.ArgumentParser()
    p.add_argument("--model", type=str, required=True, help="JSON config path")
    p.add_argument("--tpu", type=str, default="", help="unused on single host;"
                   " 'host:port,rank,size' triggers jax.distributed.initialize")
    p.add_argument("--run_mode", type=str, default="train",
                   choices=["train", "sample", "query", "web_api", "debug",
                            "debug_old"])
    p.add_argument("--steps", type=int, default=0,
                   help="override train_steps (0 = config value)")
    p.add_argument("--workers", type=int, default=None,
                   help="override cfg.web_workers (reference src/main.py:60)")
    p.add_argument("--debug_grad", action="store_true")
    p.add_argument("--port", type=int, default=8000)
    p.add_argument("--obs_port", type=int, default=None,
                   help="web_api: /metrics + /healthz exporter port "
                        "(overrides cfg.obs_port; the replica router "
                        "health-gates on this endpoint)")
    p.add_argument("--grace_deadline_s", type=float, default=30.0,
                   help="web_api: SIGTERM graceful-drain bound — finish "
                        "in-flight streams for at most this long before "
                        "exiting (docs/reliability.md)")
    p.add_argument("--profile", type=str, default="",
                   help="directory for a jax.profiler trace of a few "
                        "steady-state train steps (upgrade over the "
                        "reference's phase timers, SURVEY.md §5.1)")
    return p.parse_args(argv)


def _init_distributed(tpu_arg: str) -> None:
    """Stash ``--tpu host:port,rank,size`` into the HBNLP_DIST_* env vars;
    the actual (retried) ``jax.distributed.initialize`` happens once the
    config is loaded, via ``reliability.dist.initialize`` — one init path
    for the CLI flag, the config knobs, and the supervisor's env plumbing."""
    if "," in tpu_arg:
        from .reliability import dist
        addr, rank, size = tpu_arg.split(",")
        os.environ[dist.ENV_COORDINATOR] = addr
        os.environ[dist.ENV_PROCESS_ID] = rank
        os.environ[dist.ENV_NUM_PROCESSES] = size


def _have_dataset_files(cfg) -> bool:
    from .data import fs
    return bool(cfg.dataset_configs) and any(
        fs.glob(d["path"]) for d in cfg.dataset_configs)


def _build_state(cfg, batch, mesh=None):
    from .train import Checkpointer, Trainer, color_print
    trainer = Trainer(cfg, mesh)
    state = trainer.init(batch)
    ckpt = None
    data_state = None
    if cfg.use_checkpointing:
        ckpt = Checkpointer(os.path.join(cfg.model_path, "ckpt"),
                            cfg.max_checkpoints_keep,
                            retries=cfg.ckpt_retries)
        state, data_state = ckpt.restore(state, cfg)
        color_print(f"restored step {int(state.step)} from checkpoints"
                    if int(state.step) else "fresh initialization")
    return trainer, state, ckpt, data_state


def _dump_run_artifacts(cfg, trainer, params) -> None:
    os.makedirs(cfg.model_path, exist_ok=True)
    with open(os.path.join(cfg.model_path, "run_config.json"), "w") as f:
        json.dump({k: str(v) for k, v in cfg.dict().items()}, f, indent=2)
    census = trainer.param_census(params)
    with open(os.path.join(cfg.model_path, "model_size.info"), "w") as f:
        json.dump(census, f, indent=2)


def train(cfg, args) -> None:
    """Observability + fault-tolerance lifecycle wrapper around the step
    loop: builds the per-run ``Obs`` bundle (span tracer, /metrics +
    /healthz exporter, hang watchdog — docs/observability.md; all knobs
    default-off and inert), arms the fault-injection plan, installs the
    SIGTERM/SIGINT grace handlers (docs/reliability.md), guarantees
    ``trace.json`` export + thread shutdown on ANY exit, and delegates to
    ``_train_loop``.  A signal-triggered exit drains the async loop, cuts a
    grace checkpoint inside ``cfg.grace_deadline_s``, and exits with
    ``EXIT_PREEMPTED`` so a supervisor (tools/supervise.py) can tell
    preemption from crash."""
    from .obs import Obs
    from .obs.device_telemetry import AnomalyHalt
    from .reliability import (EXIT_ANOMALY_HALT, EXIT_PEER_LOST,
                              EXIT_PREEMPTED, GraceController, dist, faults)
    from .train import color_print
    # installed (or cleared) EVERY run: a plan must never leak across runs
    faults.install(cfg.fault_plan or None)
    obs = Obs.from_config(cfg)
    grace = GraceController(cfg.grace_deadline_s)
    try:
        # start() inside the try: a partial start (e.g. obs_port already
        # bound) must still unwind through close(), or the ambient tracer
        # would leak into every later run in this process
        obs.start()
        grace.install()
        # join the fleet (no-op single-host) BEFORE any device use: a
        # coordinator still coming up after a shared outage earns the
        # retry/backoff path, not a crash (docs/reliability.md
        # "Multi-host elasticity")
        dist.initialize(cfg)
        _train_loop(cfg, args, obs, grace)
    except dist.DistributedFailure as e:
        # a peer (or the coordinator) is gone: THIS host's state is healthy
        # and the loop already cut a checkpoint of it before re-raising —
        # exit with the distinct code so every per-host supervisor
        # relaunches the fleet in lockstep instead of backing off alone
        color_print(f"DISTRIBUTED FAILURE: {e}; exiting with code "
                    f"{EXIT_PEER_LOST} for a lockstep fleet relaunch")
        raise SystemExit(EXIT_PEER_LOST) from e
    except AnomalyHalt as e:
        # device telemetry saw non-finite gradients under
        # anomaly_policy="halt": exit with the distinct code BEFORE any
        # further checkpoint could persist poisoned state; the supervisor
        # treats it as a crash (backoff + resume from the last good save)
        color_print(f"ANOMALY HALT: {e}; exiting with code "
                    f"{EXIT_ANOMALY_HALT}")
        raise SystemExit(EXIT_ANOMALY_HALT) from e
    finally:
        grace.uninstall()
        obs.close()
    if grace.triggered:
        color_print(f"{grace.signame} handled: grace checkpoint cut; "
                    f"exiting with preemption code {EXIT_PREEMPTED}")
        raise SystemExit(EXIT_PREEMPTED)


def _finalize_profile(cfg, args, trainer, obs) -> None:
    """graftprof post-processing of a just-stopped ``--profile`` capture
    (docs/observability.md "Profile attribution"): dump the HLO op->scope
    sidecar from the kept AOT step executable, parse the Chrome trace into
    a category/scope attribution summary, persist it as
    ``<model_path>/profile_summary.json`` (the watchdog stall dump inlines
    it, ``tools/graftprof.py`` renders it), and feed the live exporter
    (``hbnlp_step_time_ms`` + per-category fractions on /metrics, comm
    fraction on /healthz).  Best-effort end to end: a malformed or absent
    trace (some toolchains never write the plugin directory) degrades to a
    log line, never an exception — the training result is already in."""
    from .obs import profile as profile_mod
    from .train import color_print
    try:
        profile_mod.write_op_map_for(trainer, args.profile)
        summary = profile_mod.capture_summary(args.profile,
                                              n_steps=cfg.profile_steps)
    except Exception as e:  # noqa: BLE001 - never fail the run for this
        color_print(f"graftprof summary failed: {type(e).__name__}: {e}")
        return
    if summary is None:
        color_print(f"no profiler trace found under {args.profile} "
                    "(plugin directory absent); skipping graftprof summary")
        return
    try:
        path = summary.save(os.path.join(cfg.model_path,
                                         "profile_summary.json"))
        d = summary.decomposition_ms_per_step
        color_print(
            f"graftprof: {d.get('total', 0.0):.3f} ms/step = "
            f"mxu {d.get('mxu', 0.0):.3f} + hbm {d.get('hbm', 0.0):.3f} + "
            f"comm {d.get('comm', 0.0):.3f} + idle {d.get('idle', 0.0):.3f} "
            f"(scope coverage {summary.attributed_scope_frac:.0%}) -> {path}")
    except Exception as e:  # noqa: BLE001
        color_print(f"graftprof summary write failed: {e}")
        return
    if obs.enabled:
        obs.record_profile(summary)


def _train_loop(cfg, args, obs, grace) -> None:
    """Async-dispatch step loop (docs/performance.md): step indices are
    computed ON HOST (``step0 + (u - u0) * m`` — no device value is read on
    the hot path; graftcheck's ``host-sync`` rule pins this), batches are
    assembled + transferred by a background ``DeviceFeeder`` thread, and
    metrics drain through a bounded ``AsyncMetricWriter`` window so up to
    ``cfg.async_inflight_steps`` updates stay dispatched-but-undrained.
    ``grace.triggered`` (SIGTERM/SIGINT) breaks the loop before the next
    dispatch; the normal tail then cuts the grace checkpoint."""
    import itertools

    run_t0 = time.time()  # TRUE run start: goodput's wall origin must
    # include mesh build, init/restore, and the step compile below

    import jax
    from .data import RunLog, dataset, to_global
    from .data.feed import DeviceFeeder
    from .data.synthetic import synthetic_text_batch
    from .obs import device_telemetry, spans
    from .reliability import dist, faults
    from .train import AsyncMetricWriter, MetricWriter, color_print
    from .train.metrics import config_hash

    have_data = _have_dataset_files(cfg)
    from .parallel import make_mesh
    # elastic runs suppress the "axis shrunk" fold warnings: when the fleet
    # resumes degraded (the device count no longer factors the declared
    # mesh — the model axis folded, or the batch-bound data axis dropped
    # devices), the mesh searcher's suggestion replaces them
    # (docs/reliability.md "Multi-host elasticity"; analysis/
    # mesh_search.py).  Non-elastic runs keep the plain warnings — running
    # a pod config on one bench chip is deliberate, not degraded.
    from .parallel.mesh import MODEL_AXIS
    elastic = dist.settings(cfg) is not None
    mesh = make_mesh(cfg, quiet=elastic)
    n_avail = len(jax.devices())
    if elastic and jax.process_index() == 0 and (
            int(dict(mesh.shape).get(MODEL_AXIS, 1)) != cfg.mesh_model
            or mesh.size < n_avail):
        # process 0 only: the search re-traces the config (seconds on a
        # flagship) and every host would log the identical suggestion
        dist.log_mesh_suggestion(cfg, mesh, n_devices=n_avail)
    # processes sharing a data-axis coordinate (pipe axis spanning hosts)
    # read the SAME dataset slice (data/feed.py::data_slice_for_process);
    # data-major topologies reduce to (process_index, process_count)
    from .data.feed import data_slice_for_process
    slice_index, slice_count = data_slice_for_process(mesh)
    # macro-batching inflates the per-step host batch by M (reference
    # dataloader_placement.py:40-44)
    local_batch = cfg.train_batch_size * cfg.macro_batching // slice_count

    if have_data:
        # probe pipeline (no prefetch thread): one template batch for init,
        # then discarded — the real pipeline is built after checkpoint
        # restore so its cursor and prefetcher start from the right place
        probe = dataset(cfg, local_batch, slice_index, slice_count,
                        prefetch=False)
        first_np = next(iter(probe))
    else:
        color_print("no dataset files found; using synthetic data")
        first_np = synthetic_text_batch(cfg, 0)
    template_gb = to_global(first_np, cfg, mesh)
    trainer, state, ckpt, data_state = _build_state(cfg, template_gb, mesh)
    if int(state.step) == 0 and cfg.current_step > 0:
        # config-forced starting step with no checkpoint (the reference reads
        # it from estimator internals and skips data accordingly,
        # src/main.py:71, dataloader_placement.py:156)
        import jax.numpy as jnp
        state = state._replace(step=jnp.asarray(cfg.current_step, jnp.int32))
    step0 = int(state.step)
    if step0 > 0:
        # a resumed (or step-forced) run must not refire step-site fault
        # rules at or behind its starting position — a sigterm@stepN plan
        # inherited by every supervisor relaunch would livelock otherwise
        faults.disarm_until("step", step0)
        # same for the distributed sites: a peer:die@stepN plan inherited
        # by the relaunched fleet would re-kill every generation forever
        faults.disarm_until("peer", step0)
        faults.disarm_until("coordinator", step0)
    pipe = None
    if have_data:
        # the real (prefetched) pipeline, with the checkpointed cursor
        # restored before the first read
        pipe = dataset(cfg, local_batch, slice_index, slice_count)
        if data_state and "pipeline" in data_state:
            pipe.load_state_dict(data_state["pipeline"])

    _dump_run_artifacts(cfg, trainer, state.params)
    # device telemetry (docs/observability.md "Device telemetry"): static
    # utilization accounting once at startup — the HLO cost analysis rides
    # the step compile the run pays anyway (the kept AOT executable then
    # serves every loop step) — plus the drain-side anomaly monitor
    telemetry_on = cfg.telemetry_interval > 0
    util = anomaly = None
    if telemetry_on:
        from .obs.device_telemetry import AnomalyMonitor
        from .train import flops as flops_mod
        anomaly = AnomalyMonitor(cfg.anomaly_policy, registry=obs.registry
                                 if obs.enabled else None)
        # template_gb is reused from init: cost analysis only LOWERS the
        # step, so no second H2D transfer of a full global batch
        util = flops_mod.utilization_for(
            trainer, state, template_gb,
            tokens_per_step=cfg.train_batch_size * max(1, cfg.macro_batching)
            * cfg.sequence_length)
        color_print(f"device telemetry on: {util.flops_per_step:.3e} "
                    f"flops/step ({util.device_kind}), anomaly_policy="
                    f"{cfg.anomaly_policy}")
    if args.profile and trainer._compiled is None:
        # graftprof attribution (docs/observability.md "Profile
        # attribution") needs the step executable's HLO metadata to map
        # trace events back to model scopes: AOT-compile now (the loop
        # reuses the kept executable, so this is the same compile the
        # first step would have paid — not an extra one) and the op-map
        # sidecar below comes for free.  Best-effort: a failing AOT path
        # only degrades per-scope attribution, never the run.
        try:
            trainer.step_cost_analysis(state, template_gb)
        except Exception as e:
            color_print(f"profile op-map pre-compile failed ({e}); "
                        "per-scope attribution will be unavailable")
    del template_gb  # release the init batch's device buffers for the run
    # deferred metrics drain: debug_train_step keeps the reference's
    # synchronous per-step prints, so it forces the window to 0
    window = 0 if cfg.debug_train_step else cfg.async_inflight_steps
    writer = AsyncMetricWriter(MetricWriter(cfg.model_path), window=window,
                               health=obs.health if obs.enabled else None,
                               registry=obs.registry if obs.enabled else None,
                               anomaly=anomaly,
                               reporter=obs.fleet_reporter)
    if util is not None:
        writer.set_utilization(util, run_start=run_t0)
        if obs.enabled:
            obs.watch_utilization(writer, util)
    # run boundary marker: restarts append to metrics.jsonl, so bench /
    # post-mortem tooling splits runs on these records
    cfg_hash = config_hash(cfg)
    # Obs.identity is cfg-resolved (env overrides the dist_* knobs): the
    # marker must agree with the /healthz identity block
    writer.write_run_start(step0, cfg_hash, identity=obs.identity)
    run_log = RunLog(cfg.model_path)
    # train_steps (and the step counter) count macro slices, reference
    # run.py:155,249: one optimizer update advances the counter by
    # macro_batching, so the update loop runs in units of M slices.
    steps = args.steps or cfg.train_steps
    m = max(1, cfg.macro_batching)
    updates_total = -(-steps // m)
    u0 = step0 // m
    ckpt_every = max(1, cfg.steps_per_checkpoint // m)
    rng = jax.random.key(cfg.data_seed)
    t0 = time.time()
    # device prefetch: the feeder's cursor snapshots ride each batch, so
    # checkpoints record CONSUMED stream position only (DeviceFeeder doc);
    # synthetic batches stay indexed by UPDATE count, as before
    if pipe is not None:
        source, state_fn = iter(pipe), pipe.state_dict
    else:
        source = (synthetic_text_batch(cfg, i) for i in itertools.count(u0))
        state_fn = None
    feeder = DeviceFeeder(source, cfg, trainer.mesh,
                          depth=cfg.device_prefetch_depth, state_fn=state_fn,
                          registry=obs.registry if obs.enabled else None)
    tracing = False
    u_done = u0  # updates actually dispatched (exhaustion can end early)
    # the try owns cleanup from the moment producer threads exist: an
    # exception anywhere below (obs wiring, window validation) must still
    # join the feeder + prefetcher, or they keep pinning device batches
    try:
        if obs.enabled:
            obs.watch_feeder(feeder)
        # steady state: cfg.profile_start >= 1 keeps the window past the
        # compile update (validated in config.py)
        profile_window = range(u0 + cfg.profile_start,
                               u0 + cfg.profile_start + cfg.profile_steps)
        if args.profile and profile_window.start >= updates_total:
            color_print(f"WARNING: --profile window starts at update "
                        f"{profile_window.start} but the run only "
                        f"dispatches updates [{u0}, {updates_total}); no "
                        f"trace will be captured — lower profile_start or "
                        f"raise --steps")
        tokens_per_update = cfg.train_batch_size * m * cfg.sequence_length
        dist_failure = None
        for u in range(u0, updates_total):
            # fault-injection site "step" keys on the GLOBAL counter so
            # e.g. sigterm@step25 survives a resume; inert without a plan
            faults.hit("step", value=step0 + (u - u0) * m)
            try:
                # distributed sites (peer:die@stepN, coordinator:drop@stepN)
                # poll on the same global counter; a detected failure stops
                # BEFORE the next dispatch so the tail below checkpoints
                # this host's healthy state, then train() exits
                # EXIT_PEER_LOST for the lockstep fleet relaunch
                dist.check_peers(step0 + (u - u0) * m)
            except dist.DistributedFailure as e:
                color_print(f"distributed failure observed at update {u} "
                            f"(step {step0 + (u - u0) * m}): {e}; cutting a "
                            "checkpoint before the fleet relaunch")
                dist_failure = e
                break
            if grace.triggered:
                # preemption: stop BEFORE dispatching another update — the
                # loop tail below cuts the grace checkpoint at the last
                # completed step and the process exits EXIT_PREEMPTED
                color_print(f"{grace.signame or 'signal'} received: "
                            f"stopping at update {u} "
                            f"(step {step0 + (u - u0) * m}) for the grace "
                            "checkpoint")
                break
            try:
                with spans.span("feed", update=u):
                    gb = next(feeder)
            except StopIteration:
                # single-epoch dataset exhausted (the reference's sequential
                # reader dies on OutOfRange here, inputs.py:540-541): stop
                # CLEANLY — final checkpoint below, clear message, no
                # traceback.  Set repeat_dataset=true for deterministic
                # epoch wrap-around.
                color_print(f"dataset exhausted after update {u} "
                            f"(step {step0 + (u - u0) * m}); stopping — set "
                            "repeat_dataset=true for multi-epoch runs")
                break
            if args.profile and u == profile_window.start:
                jax.profiler.start_trace(args.profile)
                tracing = True
            host_step = step0 + (u - u0) * m  # counter BEFORE this update
            grad_scale = None
            if telemetry_on:
                # fault site "grads": the caller-implemented "nan" action
                # feeds a NaN gradient scale into this one step so the
                # anomaly policies are drillable (grads:nan@stepN) — params
                # stay clean because skip_step masks the update in-graph
                if "nan" in faults.take("grads", value=host_step):
                    grad_scale = np.nan
            with spans.span("step", update=u):
                state, metrics = trainer.step(state, gb,
                                              jax.random.fold_in(rng, u),
                                              grad_scale=grad_scale)
            u_done = u + 1
            if telemetry_on:
                # host-side thinning: norm-class telemetry keys off the
                # telemetry_interval grid never transfer; sentinels always
                # do.  The grid keys on the GLOBAL update index so a
                # resumed run's norm rows land on the same steps as an
                # uninterrupted one's
                metrics = device_telemetry.thin(metrics, u,
                                                cfg.telemetry_interval)
            writer.write(host_step, metrics)
            if obs.enabled:
                obs.step_dispatched(tokens_per_update)
            if tracing and u + 1 >= profile_window.stop:
                # the window's last update just dispatched (exactly
                # profile_steps captured): drain the whole in-flight window
                # (blocks until every dispatched step finished) so the
                # trace captures complete steps, then stop
                writer.flush()
                jax.profiler.stop_trace()
                tracing = False
                color_print(f"profiler trace written to {args.profile}")
                _finalize_profile(cfg, args, trainer, obs)
            if cfg.debug_train_step or (u + 1) % 10 == 0:
                # debug_train_step: per-step prints (reference run.py:252-261)
                # showing the most recent COMPLETED loss — never a blocking
                # read of the in-flight one
                rate = (u + 1 - u0) / (time.time() - t0)
                loss_s = ("..." if writer.last_loss is None
                          else f"{writer.last_loss:.4f}")
                color_print(f"step {host_step + m} loss {loss_s} "
                            f"({rate:.2f} updates/s)")
            if ckpt is not None and (u + 1) % ckpt_every == 0:
                writer.flush()  # metrics.jsonl consistent with the checkpoint
                data_state = ({"pipeline": feeder.state_dict()}
                              if pipe is not None else None)
                # declared pause: a multi-second save must not read as a
                # stall on /healthz or trip the watchdog
                with spans.span("checkpoint", step=host_step + m), \
                        obs.pause("checkpoint"):
                    ckpt.save(state, data_state,
                              master_dtype=cfg.storage_dtype,
                              config_hash=cfg_hash)
                if obs.enabled:
                    # memory_stats() can sync the device, so it samples at
                    # the checkpoint cadence, never per step
                    obs.sample_device_memory()
    finally:
        # pipe first: its close() wakes a feeder producer blocked on the
        # host-prefetch queue, so the feeder join below cannot stall
        if pipe is not None and hasattr(pipe, "close"):
            pipe.close()
        feeder.close()
        try:
            # an exception exit (OOM, NaN guard, Ctrl-C) must still persist
            # the in-flight window's COMPLETED updates — those are exactly
            # the losses a post-mortem needs
            writer.flush()
        except device_telemetry.AnomalyHalt:
            # the halt sentinel drained during this exit flush (a short run
            # can end before the deferred window ever drains the anomalous
            # step): propagate — the tail below must NOT cut a checkpoint
            # of potentially-poisoned params
            raise
        except Exception:
            pass  # the failing step's own metrics may be unmaterializable
    if tracing:  # run ended inside the profile window
        writer.flush()
        jax.profiler.stop_trace()
        color_print(f"profiler trace written to {args.profile}")
        _finalize_profile(cfg, args, trainer, obs)
    if ckpt is not None:
        # on a grace exit this IS the grace checkpoint (save() waits on the
        # orbax barrier before writing sidecar + manifest, so returning
        # means durable — within GraceController's deadline timer)
        with spans.span("checkpoint", step=step0 + (u_done - u0) * m), \
                obs.pause("checkpoint"):
            ckpt.save(state,
                      {"pipeline": feeder.state_dict()} if pipe else None,
                      master_dtype=cfg.storage_dtype,
                      config_hash=cfg_hash)
        if obs.enabled:
            obs.sample_device_memory()
    # rows consumed per update = batch * macro_batching (grad_accumulation
    # only splits the delivered batch, it does not consume more data);
    # record DISPATCHED updates so exhaustion-shortened runs replay right
    run_log.append(steps=u_done - u0, batch_size=cfg.train_batch_size,
                   slice_count=slice_count, ctx=cfg.sequence_length,
                   grad_accumulation=cfg.macro_batching,
                   interleave_size=cfg.interleaved_datasets,
                   token_patch_size=cfg.token_patch_size)
    run_log.save()
    writer.close()  # drains any remaining window entries first
    if u_done > u0:
        color_print(f"trained {u_done - u0} updates; host blocked "
                    f"{writer.host_blocked_s:.2f}s in metric drains "
                    f"(window {window})")
    if dist_failure is not None:
        # the checkpoint above persisted this host's progress; now surface
        # the distributed failure so train() maps it to EXIT_PEER_LOST
        raise dist_failure


def _params_for_serving(cfg):
    if cfg.use_video:
        from .data.synthetic import synthetic_video_batch
        batch = _np_to_nt(synthetic_video_batch(cfg, 0), cfg)
    else:
        from .utils import random_text_batch
        batch = random_text_batch(cfg)
    if cfg.use_checkpointing:
        from .train import Checkpointer, Trainer
        state = Trainer(cfg).init(batch)
        state, _ = Checkpointer(os.path.join(cfg.model_path, "ckpt")).restore(state, cfg)
        params = state.params
    else:
        from .models import init_params
        params, _ = init_params(cfg, batch)
    from .models import pipeline_params_stacked, unstack_pipeline_params
    if pipeline_params_stacked(cfg, params):
        # pipeline-trained checkpoints store body params stage-stacked;
        # every serving/sampling consumer runs the plain sequential chain
        params = unstack_pipeline_params(cfg, params)
    return params


def _video_batches(cfg):
    """Real video batches when dataset files exist, else synthetic frames."""
    from .data import fs
    from .data.synthetic import synthetic_video_batch
    from .data.video import VideoPipeline
    globs = [d["path"] for d in cfg.dataset_configs if d.get("type") == "video"]
    paths = [p for g in globs for p in fs.glob(g)]
    if paths:
        return iter(VideoPipeline(cfg, cfg.train_batch_size, paths=paths))
    import itertools
    return (synthetic_video_batch(cfg, i) for i in itertools.count())


def _np_to_nt(np_batch, cfg):
    import jax.numpy as jnp
    from .data.feed import axes_for
    from .nd import NT
    return {k: NT(jnp.asarray(v), axes_for(k, v, cfg))
            for k, v in np_batch.items()}


def _sample_video(cfg, args) -> None:
    """Video sample mode: render input/output ``.avi`` files from real (or
    synthetic) frame streams (reference interface.py:101-139)."""
    import numpy as np
    from .infer.sampler import autoregressive_video, forward_logits
    from .serve.sample import render_video
    from .train import color_print
    params = _params_for_serving(cfg)
    batches = _video_batches(cfg)
    outdir = os.path.join(cfg.model_path, "samples")
    os.makedirs(outdir, exist_ok=True)
    t = cfg.time_patch_size
    for i in range(cfg.num_of_sample):
        np_batch = next(batches)
        nt = _np_to_nt(np_batch, cfg)
        if cfg.use_autoregressive_sampling:
            _, frames = autoregressive_video(cfg, params, nt)
            out = np.array(frames[0], np.float32, copy=True)[:t]
            # context positions are raw 0..255; generated ones are sigmoid
            # outputs in [0,1] (the reference blends the same way,
            # inference.py:39-40)
            pos0 = min(cfg.initial_autoregressive_position, t)
            out[:pos0] /= 255.0
        else:
            _, frame_out = forward_logits(cfg, params, nt)
            out = np.asarray(frame_out[0], np.float32)[:t]
            inp = np.asarray(np_batch["frame"][0], np.float32)[1:t + 1] / 255.0
            render_video(cfg, inp, os.path.join(outdir, f"sample_{i}_input.avi"))
        path = render_video(cfg, out,
                            os.path.join(outdir, f"sample_{i}_output.avi"))
        color_print(f"sample_idx: {i} -> {path}")


def sample(cfg, args) -> None:
    if cfg.debug_sample:
        # sample mode with debug_sample prints dataset-driven similarity
        # (reference interface.py:144-152)
        return debug_old(cfg, args)
    if cfg.model_mode == "jannet" and cfg.use_video:
        return _sample_video(cfg, args)
    from .serve import CompletionEngine, render_text_samples
    params = _params_for_serving(cfg)
    if not cfg.use_autoregressive_sampling:
        # dataset-driven single forward: print target vs one-step prediction
        # (reference interface.py:165-170); synthetic only when no dataset
        # files exist
        import jax
        import numpy as np
        from .data import dataset
        from .data.synthetic import synthetic_text_batch
        from .infer.sampler import make_single_forward
        from .serve.interface import tokenizer_for
        tok = tokenizer_for(cfg)
        fwd = make_single_forward(cfg, params)
        if _have_dataset_files(cfg):
            batches = iter(dataset(cfg, cfg.train_batch_size, prefetch=False))
        else:
            import itertools
            batches = ({"token_x": synthetic_text_batch(cfg, i)["token_x"]}
                       for i in itertools.count())
        for i in range(cfg.num_of_sample):
            nt = _np_to_nt(next(batches), cfg)["token_x"]
            out = np.asarray(fwd(nt, np.int32(0), np.float32(0.0),
                                 jax.random.key(i)))
            print("target:")
            print(tok.decode(np.asarray(nt.x)[0].reshape(-1)))
            print("\nsample:")
            print(tok.decode(out[0].reshape(-1)))
        return
    engine = CompletionEngine(cfg, params)
    for i in range(cfg.num_of_sample):
        out = engine.complete_tokens([int(cfg.concat_token)])
        render_text_samples(out[None], engine.tokenizer)


def query(cfg, args) -> None:
    from .serve import repl
    repl(cfg, _params_for_serving(cfg))


def web_api(cfg, args) -> None:
    import signal
    import threading

    from .serve import serve as rest_serve
    print(f"serving on :{args.port}", flush=True)
    server = rest_serve(cfg, _params_for_serving(cfg), port=args.port,
                        obs_port=getattr(args, "obs_port", None),
                        background=True)
    grace = float(getattr(args, "grace_deadline_s", 30.0))
    stopped = threading.Event()

    def _drain_bg():
        server.drain(grace)
        stopped.set()

    def _on_sigterm(signum, frame):
        # drain off the signal frame: drain() blocks on in-flight streams
        # then shutdown()s, neither of which belongs in a handler
        threading.Thread(target=_drain_bg, daemon=True,
                         name="drain").start()

    try:
        signal.signal(signal.SIGTERM, _on_sigterm)
    except ValueError:  # not the main thread (embedded/test use)
        pass
    try:
        # serve_forever runs on the background thread; park here until a
        # SIGTERM drain stops the server
        while not stopped.wait(timeout=1.0):
            pass
    except KeyboardInterrupt:
        server.drain(grace)
    finally:
        server.server_close()


def debug(cfg, args) -> None:
    """Self-similarity nondeterminism check (reference interface.py:283-302)."""
    from .serve import CompletionEngine, similarity_score
    # debug sampling forces greedy autoregressive mode (reference
    # src/main.py:75-78)
    cfg.use_autoregressive_sampling = True
    cfg.sampling_temperature = 0
    params = _params_for_serving(cfg)
    n_samples = max(2, min(4, cfg.equal_debugging_items_per_check))
    if cfg.use_video:
        # video self-similarity: identical greedy rollouts must produce
        # bit-equal frames
        import jax

        from .data.synthetic import synthetic_video_batch
        from .infer.sampler import autoregressive_video
        batch = _np_to_nt(synthetic_video_batch(cfg, 0), cfg)
        fn = jax.jit(lambda p, b: autoregressive_video(cfg, p, b)[1])
        samples = [np.asarray(fn(params, batch), np.float32)
                   for _ in range(n_samples)]
        if not all(np.isfinite(s).all() for s in samples):
            raise SystemExit("non-finite frames generated — check the "
                             "checkpoint, not sampler determinism")
    else:
        engine = CompletionEngine(cfg, params, force_rebuild=True)
        prompt = list(range(min(16, cfg.vocab_size)))
        samples = [np.asarray(engine.complete_tokens(prompt, temperature=0.0))
                   for _ in range(n_samples)]
    score = similarity_score(samples)
    print(f"similarity: {score * 100:.2f}%")
    if score < 1.0:
        raise SystemExit("nondeterministic sampling detected")


def debug_old(cfg, args) -> None:
    """Dataset-driven similarity sampling (reference src/main.py:37-38,
    interface.py:144-152): one real dataset window duplicated to batch 2,
    greedy autoregressive samples, % agreement printed with both decodings."""
    import jax
    import numpy as np

    from .data import dataset
    from .infer.sampler import make_text_sampler
    from .nd import NT
    from .serve import similarity_score
    from .serve.interface import TEXT_AXES, tokenizer_for
    from .train import color_print

    params = _params_for_serving(cfg)
    if _have_dataset_files(cfg):
        np_batch = next(iter(dataset(cfg, 1)))
        token_x = np.asarray(np_batch["token_x"])[:1]
    else:
        color_print("no dataset files found; using synthetic prompt")
        from .data.synthetic import synthetic_text_batch
        token_x = synthetic_text_batch(cfg, 0)["token_x"][:1, :cfg.sequence_length
                                                          // cfg.token_patch_size]
    pos0 = max(1, min(cfg.initial_autoregressive_position,
                      cfg.sequence_length - 1)) // cfg.token_patch_size
    both = np.concatenate([token_x, token_x], axis=0)  # batch 2, same prompt
    sampler = make_text_sampler(cfg, params)
    out = np.asarray(sampler(NT(jax.numpy.asarray(both), TEXT_AXES),
                             np.int32(pos0), np.float32(0.0),
                             jax.random.key(0)))
    score = similarity_score([out[0], out[1]])
    tok = tokenizer_for(cfg)
    print(f"similarity score: {score * 100:.0f}%\n")
    color_print("Prompt:")
    print(tok.decode(out[0, :pos0].reshape(-1)))
    color_print("Output:")
    print(tok.decode(out[0, pos0:].reshape(-1)).rstrip())
    if score < 1.0:
        raise SystemExit("nondeterministic sampling detected")


RUN_MODE_FNS = {"train": train, "sample": sample, "query": query,
                "web_api": web_api, "debug": debug, "debug_old": debug_old}


def main(argv: typing.Optional[typing.Sequence[str]] = None) -> None:
    args = parse_args(argv)
    _init_distributed(args.tpu)
    from .config import Config
    with open(args.model) as f:
        raw = json.load(f)
    if args.run_mode != "train":
        # serving modes force batch size 1 (2 + greedy AR for debug_old) —
        # reference src/main.py:74-80
        raw["train"] = False
        if args.run_mode == "debug_old":
            raw["train_batch_size"] = 2
            raw["use_autoregressive_sampling"] = True
            raw["sampling_temperature"] = 0
            raw["debug_sample"] = True
        else:
            raw["train_batch_size"] = 1
    cfg = Config(raw)
    # every run mode joins the fleet (no-op single-host): serving/sampling
    # on a multi-host pod must see the global device set, exactly as the
    # pre-elastic --tpu path did; train() re-checks (idempotent) for
    # callers that enter it directly.  An init give-up maps to
    # EXIT_PEER_LOST here too — after a shared outage the coordinator may
    # simply be slow, and the supervisors must relaunch the fleet in
    # lockstep rather than classify every host as crash-looping
    from .reliability import EXIT_PEER_LOST, dist, faults
    # the plan must be armed BEFORE the init or the documented
    # dist_init:fail@N drill is silently inert on the CLI path; train()
    # re-installs the same plan (harmless — the init below short-circuits
    # on its second call, so a fired dist_init rule cannot refire)
    faults.install(cfg.fault_plan or None)
    try:
        dist.initialize(cfg)
    except dist.DistributedFailure as e:
        print(f"DISTRIBUTED INIT FAILURE: {e}; exiting with code "
              f"{EXIT_PEER_LOST} for a lockstep fleet relaunch")
        raise SystemExit(EXIT_PEER_LOST) from e
    from .utils import enable_compilation_cache
    enable_compilation_cache(cfg.compilation_cache_dir)
    if args.debug_grad:
        cfg.debug_gradients = True
    if args.workers is not None:  # reference src/main.py:60
        cfg.web_workers = args.workers
    RUN_MODE_FNS[args.run_mode](cfg, args)
